"""Event-driven simulation engine: one lax.scan step per event, vmapped over
runs, executed in fixed-step *chunks* with a host loop between them.

Reformulates the reference event loop (``RunSimulation``, main.cpp:128-192) as
``jax.lax.scan`` over the O(1) automaton of :mod:`tpusim.state`:

  reference iteration                      scan step
  ------------------------------------     ------------------------------------
  while (cur_time == next_block_time)      one found-event per step; the notify
      PickFinder + FoundBlock              is skipped while another same-ms find
      next_block_time += interval          is due, reproducing the while-drain
  BestChain + NotifyBestChain(all)         notify() (flush, best, reveal, reorg)
  best_chain_size = best.size()            best_height_prev
  cut-through to min(next_block,           t = max(min(next_block_time,
      EarliestArrival)                         earliest_arrival), t)

Chunking (the TPU-native shape of "long context"): a year-long run is ~105k
events, and int32 relative time only spans ~12 days, so the engine executes a
fixed number of scan steps per jitted call, re-bases every run's clock to 0
(state.rebase), and lets the host carry absolute elapsed time in int64 numpy.
This keeps every on-device value 32-bit (TPUs emulate 64-bit at a large
slowdown), keeps each device call seconds-long (no RPC/timeout cliffs on
year-long simulations), compiles ONE chunk program reused for any duration,
and stops as soon as every run in the batch has actually finished — rather
than provisioning a Poisson upper bound of steps for all runs.

RNG is counter-based: chunk ``c`` of a run draws its (winner, interval) words
as ``random.bits(fold_in(run_key, 1 + c), (steps, 2))`` — one batched threefry
per chunk instead of per-step key folding — so draws are independent of
execution order and of how runs are batched, replacing the reference's two
per-run xoroshiro streams (main.cpp:131-134). ``chunk_steps`` IS part of the
sampling identity (it sets the step->key mapping), which is why it is
serialized with the config and covered by the checkpoint fingerprint.
Under the default ``SimConfig.rng_batch`` the *mapping* of those words —
winner index from the threshold compare, interval ms from the exponential —
is also hoisted out of the event loop into one vectorized pass per chunk
(and, for rng="xoroshiro", into a K-wide consumption-order-preserving
lookahead per superstep), so the serial scan body consumes finished draws;
the words, their per-event assignment and every statistic are bit-identical
to the per-event mapping (tests/test_rng_batch.py).
"""

from __future__ import annotations

import logging
import math
from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P
from .compat import shard_map

from .chaos import InjectedHang, PipelineStallError, fetch_with_deadline
from .config import SimConfig
from .convergence import STATS as MOMENT_STATS, moment_keys
from .sampling import interval_from_bits, winner_from_bits
from .state import (
    TIME_CAP,
    SimParams,
    SimState,
    earliest_arrival,
    final_stats,
    found_block,
    init_state,
    make_params,
    notify,
    rebase,
    rebase_counts,
)

__all__ = [
    "Engine", "SimCounters", "default_n_steps", "resolve_superstep",
    "auto_superstep", "AUTO_SUPERSTEP_TABLE", "DEPTH_BUCKETS",
]

logger = logging.getLogger("tpusim")

#: Per-batch int32 block-count sums stay exact below this many blocks.
_I32_SUM_GUARD = 2**31 - 1

#: Auto superstep width K (events unrolled per scan step / kernel loop
#: iteration), as a MEASURED table keyed by (jax backend platform, mode
#: kind) instead of one hard-coded constant: the K x batch ablation of
#: `scripts/roofline.py --k-list 1,2,4,8,16` (chained-chunk min-of-repeats,
#: the repo's only sanctioned kernel timing) is the measurement path, and
#: each entry names the artifact it came from. Re-tune by re-running the
#: ablation on the target platform and editing the row — resolve_superstep
#: halves a table value until it divides the step budget, so entries can
#: assume the 64-aligned auto chunk_steps / Pallas step_block.
AUTO_SUPERSTEP_TABLE: dict[tuple[str, str], int] = {
    # This container's 2-core CPU, batched-RNG gather engine (PR 10
    # re-ablation, artifacts/roofline_cpu.json): fast mode keeps K=2 at the
    # production batches (int16-rebased batch 256: 839k ev/s at K=2 vs 701k
    # at K=1; K=4's 879k is within round noise of K=2) — only the small
    # batch-64 cell prefers K=1 — and exact mode still regresses at every
    # K>1 (int16-rebased batch 256: 323k at K=1 vs 264k at K=2; the
    # headline A/B at 512 runs agrees).
    ("cpu", "fast"): 2,
    ("cpu", "exact"): 1,
    # v5e round-5 on-chip ablation (artifacts/perf_tpu.jsonl): fast kernel
    # peaks at K=2; exact regresses above 1. Pre-batched-RNG numbers — the
    # on-TPU retune rides the next-TPU-window checklist (ROADMAP).
    ("tpu", "fast"): 2,
    ("tpu", "exact"): 1,
}

#: Fallback for platforms with no measured row (e.g. gpu): the historical
#: defaults, conservative on the side of the pre-PR-6 measurements.
_AUTO_SUPERSTEP_FALLBACK = {"fast": 2, "exact": 1}


def auto_superstep(exact: bool, platform: str | None = None) -> int:
    """The measured auto-K for this platform and mode kind (table above).
    ``platform`` defaults to the active jax backend — resolved lazily, at
    engine-construction time, so importing this module never initializes an
    XLA backend (worker processes must call jax.distributed.initialize
    first)."""
    if platform is None:
        platform = jax.default_backend()
    kind = "exact" if exact else "fast"
    return AUTO_SUPERSTEP_TABLE.get((platform, kind), _AUTO_SUPERSTEP_FALLBACK[kind])


def resolve_superstep(requested: int | None, divisor: int, *, exact: bool = False) -> int:
    """The unroll width actually compiled: an explicit request must divide
    ``divisor`` (chunk_steps for the scan engine, step_block for the Pallas
    kernel) exactly — a silent trim would compile a different program than
    the one asked for; the auto default (the measured per-platform table of
    :func:`auto_superstep`) halves itself until it divides (K=1 always
    does)."""
    if requested is not None:
        if divisor % requested:
            raise ValueError(
                f"superstep ({requested}) must divide {divisor} (the resolved "
                f"chunk_steps / step_block)"
            )
        return requested
    k = auto_superstep(exact)
    while divisor % k:
        k //= 2
    return max(k, 1)


#: Reorg-depth histogram buckets: depths 1..DEPTH_BUCKETS-1 get their own
#: bucket, the last bucket is open-ended (depth >= DEPTH_BUCKETS). Sized so an
#: honest roster's 1-2-deep races and a selfish roster's burst reveals are
#: both resolved without widening the carried aux tree meaningfully.
DEPTH_BUCKETS = 8


class SimCounters(NamedTuple):
    """Device-side simulation telemetry, per run, accumulated event-by-event
    in the carried aux tree — the counters ride the same HBM round trip as
    the simulation state (scan carry / VMEM-resident kernel leaves), so
    collecting them costs one O(M) reduction per event and ~(12 + 4*(M + 8))
    bytes per run of extra traffic, invisible next to the ~KB state tree.

    The scan engine and the Pallas kernel compute these from the same
    quantities at the same program points, so they are pinned bit-equal by
    tests (tests/test_cli_report.py) like every other output.
    """

    #: max over events of own blocks popped by a single reorg (the stale
    #: increment of one adoption) — the depth proxy the O(1) representation
    #: supports: lca heights are not tracked, own-block pops are.
    reorg_max: jax.Array  # int32 []
    #: events in which at least one block went stale (a reorg with losses).
    stale_events: jax.Array  # int32 []
    #: events for which this run was active (t < cap): occupancy numerator.
    #: The complement is scan steps burned on a frozen run — the quantity
    #: the chunk_steps sizing rationale above reasons about, now measured.
    active_steps: jax.Array  # int32 []
    #: per-miner stale-event counts: events in which miner m lost >= 1 own
    #: block (several miners can lose in one event, so the vector's sum can
    #: exceed ``stale_events``). The per-miner breakdown the aggregate
    #: dashboards lacked when everything collapsed to max/sum.
    stale_by_miner: jax.Array  # int32 [M]
    #: histogram of the per-event max single-adopter pop count (the same
    #: quantity reorg_max maxes): bucket d-1 counts events of depth d,
    #: bucket DEPTH_BUCKETS-1 counts depth >= DEPTH_BUCKETS.
    reorg_depth_hist: jax.Array  # int32 [DEPTH_BUCKETS]


def init_counters(n_miners: int) -> SimCounters:
    z = jnp.zeros((), jnp.int32)
    return SimCounters(
        z, z, z,
        jnp.zeros((n_miners,), jnp.int32),
        jnp.zeros((DEPTH_BUCKETS,), jnp.int32),
    )


def _count_step(ctr: SimCounters, old: SimState, new: SimState, cap: jax.Array) -> SimCounters:
    """Fold one event into the counters from the state delta — ``stale`` only
    moves in the notify reorg, so ``new.stale - old.stale`` is exactly the
    per-miner pop count of this event's adoptions (zero when the sweep is
    gated off or the run is frozen)."""
    # int32 regardless of the packed count dtype: the counter leaves stay
    # wide (active_steps alone outgrows int16 within a run).
    d = (new.stale - old.stale).astype(jnp.int32)
    dmax = jnp.max(d)
    bucket = jnp.minimum(dmax, DEPTH_BUCKETS) - 1
    return SimCounters(
        reorg_max=jnp.maximum(ctr.reorg_max, dmax),
        stale_events=ctr.stale_events + (dmax > 0).astype(jnp.int32),
        active_steps=ctr.active_steps + (old.t < cap).astype(jnp.int32),
        stale_by_miner=ctr.stale_by_miner + (d > 0).astype(jnp.int32),
        reorg_depth_hist=ctr.reorg_depth_hist
        + ((jnp.arange(DEPTH_BUCKETS) == bucket) & (dmax > 0)).astype(jnp.int32),
    )


#: run_batch output keys whose cross-batch (and head/tail split) merge is a
#: max, not a sum — combine_sums() is the one merge rule for stat dicts.
_MAX_KEYS_SUFFIX = "_max"


def combine_sums(a: dict[str, np.ndarray], b: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Merge two run_batch outputs over disjoint run sets: additive for the
    stat sums, elementwise max for the ``*_max`` telemetry keys (a batch's
    busy-chunk count / deepest reorg is the max over its runs, and run
    behavior is batching-invariant under the counter-based RNG), and
    run-axis concatenation for the per-run arrays — the flight-recorder
    keys and, since grid packing (tpusim.packed), any ``*_per_run`` leaf:
    a packed engine's raw outputs keep the runs (= segment) axis intact, so
    splitting a packed batch and re-concatenating is bit-equal to one
    dispatch and the per-point segment reduction downstream never sees the
    split (pinned by tests/test_packed_sweep.py).

    The streaming-moment keys (``stats_n``, ``stats_<stat>_m1/m2`` —
    tpusim.convergence) ride the additive branch deliberately: they are
    int64 fixed-point sums, so this merge is exact, hence associative and
    permutation-invariant bit-for-bit — the property that keeps the
    convergence estimator identical across batch splits and the pallas
    head/tail split (pinned by tests/test_convergence.py). Per-POINT
    segment leaves (a leading points axis over additive sums, the packed
    sweep's device segment reduction) ride it too: integer segment sums
    over disjoint run sets merge exactly, whatever the split."""
    def merge(k):
        if k.startswith("flight_") or k.endswith("_per_run"):
            return np.concatenate([np.asarray(a[k]), np.asarray(b[k])])
        if k.endswith(_MAX_KEYS_SUFFIX):
            return np.maximum(a[k], b[k])
        return a[k] + b[k]

    return {k: merge(k) for k in a}


def _host_reduce_telemetry(out: dict[str, np.ndarray], busy_chunks: int) -> None:
    """Collapse the per-run counter leaves into the telemetry summary keys
    (host-side int64: an int32 device sum of active_steps would overflow at
    ~10k runs x 200k steps)."""
    out["tele_reorg_depth_max"] = np.int64(np.max(out.pop("tele_reorg_depth_per_run")))
    out["tele_stale_events_sum"] = np.int64(
        out.pop("tele_stale_events_per_run").astype(np.int64).sum()
    )
    out["tele_active_steps_sum"] = np.int64(
        out.pop("tele_active_steps_per_run").astype(np.int64).sum()
    )
    out["tele_stale_by_miner_sum"] = (
        out.pop("tele_stale_by_miner_per_run").astype(np.int64).sum(axis=0)
    )
    out["tele_reorg_depth_hist_sum"] = (
        out.pop("tele_reorg_depth_hist_per_run").astype(np.int64).sum(axis=0)
    )
    out["tele_chunks_max"] = np.int64(busy_chunks)


def _host_reduce_sums(out: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    """Collapse the per-run float32 ratio leaves into float64 host sums —
    the finalize boundary where ~1e-5 float32 accumulation noise on 8k-run
    batches is eliminated (see finalize_fn) — and spill the streaming-moment
    telemetry keys (tpusim.convergence.moment_keys: exact int64 first/second
    moments per miner of blocks_found / blocks_share / stale_rate, plus the
    run count) from the same per-run leaves. Like the counter reduction in
    ``_host_reduce_telemetry``, the moment sums happen at this host boundary
    in 64-bit — an on-device 32-bit sum of squared counts would overflow
    within one large batch. A dict without per-run leaves (the
    multi-controller device-psum path) passes through unchanged and emits no
    moment keys."""
    per: dict[str, np.ndarray] = {}
    for name, _, _ in MOMENT_STATS:
        per_run = out.pop(name + "_per_run", None)
        if per_run is not None:
            per[name] = per_run
    for name in ("blocks_share", "stale_rate"):
        # The float32 ratio leaves also feed the statistics path (their
        # float64 host sums); blocks_found's stat sum is the exact device
        # int sum and needs no host fold.
        if name in per:
            out[name + "_sum"] = per[name].astype(np.float64).sum(axis=0)
    if per:
        if len(per) != len(MOMENT_STATS):
            # Partial presence is a wiring bug, not a legal path: the psum
            # path produces NO per-run leaves, the finalize path produces
            # all of them. Fail loud so extending convergence.STATS cannot
            # silently stop (or half-emit) the moment telemetry.
            raise RuntimeError(
                f"streaming-moment wiring incomplete: finalize produced "
                f"per-run leaves {sorted(per)} but convergence.STATS "
                f"declares {[n for n, _, _ in MOMENT_STATS]}; add the "
                f"missing <stat>_per_run leaf to finalize_fn and the mesh "
                f"out_specs"
            )
        out.update(moment_keys(per))
    return out


def apply_count_rebase(state: SimState, cb, fr, *, batched: bool = False):
    """The chunk-boundary count re-base, shared by every site that runs it
    (both scan chunk_fn rng paths per-run, the pallas chunk batched):
    re-base the count leaves, fold the subtracted per-owner base into the
    carried ``cb`` accumulator, and advance the flight recorder's absolute
    height origin by the total. Returns ``(state, cb, fr)``."""
    rc = jax.vmap(rebase_counts) if batched else rebase_counts
    state, delta = rc(state)
    cb = cb + delta
    if fr is not None:
        from .flight import advance_height_base

        fr = advance_height_base(fr, jnp.sum(delta, axis=-1, dtype=jnp.int32))
    return state, cb, fr


def default_n_steps(duration_ms: int, block_interval_s: float) -> int:
    """Upper bound on event-loop iterations for one run: found events +
    arrival events <= 2x the block count, sized at mean + 8 sigma of the
    Poisson block count (per-run exceedance ~1e-13)."""
    mu = duration_ms / (block_interval_s * 1000.0)
    return int(2.0 * (mu + 8.0 * math.sqrt(mu + 1.0))) + 16


def _step_event(
    state: SimState, w: jax.Array, dt: jax.Array, params: SimParams, cap: jax.Array,
    any_selfish: bool, fr=None, gather: bool = True,
):
    """One event given this step's (winner, interval) draws: a block find if
    one is due at ``t``, then the notify sweep, then cut-through time advance.
    ``cap`` freezes the run when it passes its chunk-relative end (duration
    reached, or TIME_CAP pending a re-base).

    Event gating is pushed *into* the updates instead of post-hoc tree
    selects: a winner index of -1 makes ``found_block`` an exact identity, and
    ``notify(do=...)`` gates its flush/reveal/adopt masks — so every state
    leaf is computed and written once per step.

    ``fr`` (a :class:`tpusim.flight.FlightRecorder`, or None when recording
    is compiled out) folds this event into the flight ring. It is threaded
    as the second return value either way — None is an empty pytree, so the
    recorder-less program is unchanged by the uniform arity.
    """
    active = state.t < cap
    found_due = active & (state.t == state.next_block_time)
    state1 = found_block(
        state, params, jnp.where(found_due, w, jnp.int32(-1)), any_selfish,
        gather=gather,
    )
    nbt = jnp.where(found_due, state.t + dt, state.next_block_time)
    state1 = state1._replace(next_block_time=nbt)

    # Another find due at the same millisecond: defer the notify, matching the
    # reference's while-drain (main.cpp:151-157). Between two same-ms finds no
    # published state changes (all stamps are in the future), so deferral is
    # only load-bearing for 0ms-propagation configs.
    do_notify = active & ~(found_due & (nbt == state.t))
    state2 = notify(
        state1, params, do=do_notify, any_selfish=any_selfish, gather=gather
    )

    # Cut-through to the next event (main.cpp:173-182). The max() guard keeps
    # time in place when a same-ms find is still pending (unflushed arrivals
    # could otherwise pull the min below cur_time).
    new_t = jnp.maximum(jnp.minimum(state2.next_block_time, earliest_arrival(state2)), state2.t)
    out = state2._replace(t=jnp.where(active, new_t, state.t))
    if fr is not None:
        from .flight import record_step

        fr = record_step(
            fr, old=state, found=state1, new=out, w=w, found_due=found_due,
            do=do_notify,
        )
    return out, fr


def _step(
    state: SimState, bits2: jax.Array, params: SimParams, cap: jax.Array,
    any_selfish: bool, fr=None, gather: bool = True,
):
    """Threefry step: one (winner, interval) uint32 word pair is burned per
    scan step whether or not a find is due — that is what makes the draws
    counter-based and order-independent (module docstring)."""
    w = winner_from_bits(bits2[0], params.thresholds)
    dt = interval_from_bits(bits2[1], params.mean_interval_ms)
    return _step_event(state, w, dt, params, cap, any_selfish, fr=fr, gather=gather)


def _step_xoro(state: SimState, xi, xw, params: SimParams, cap: jax.Array,
               any_selfish: bool, fr=None, gather: bool = True):
    """xoroshiro128++ step: two sequential per-run streams (interval, winner)
    advanced ONLY when the draw is consumed (a find is due this step), exactly
    mirroring the native backend's consumption pattern
    (native/simcore.cpp simulate_run) so tiny configs A/B bit-for-bit."""
    from .xoroshiro import (
        interval_ms_from_word,
        next_words,
        select_streams,
        winner_from_word64,
    )
    from .state import INTERVAL_CAP

    active = state.t < cap
    found_due = active & (state.t == state.next_block_time)
    xw2, wh, wl = next_words(xw)
    w = winner_from_word64(wh, wl, params.thr64_hi, params.thr64_lo)
    xi2, ih, il = next_words(xi)
    dt = interval_ms_from_word(ih, il, params.mean_interval_ms, float(INTERVAL_CAP))
    xi = select_streams(found_due, xi2, xi)
    xw = select_streams(found_due, xw2, xw)
    state2, fr = _step_event(state, w, dt, params, cap, any_selfish, fr=fr,
                             gather=gather)
    return state2, xi, xw, fr


# Design note (negative result, kept so it is not re-attempted): stepping one
# *block* per scan step with all arrival-time notifies batched into the next
# find's pre-find flush is observationally exact for chains, shares and
# found counts (adoption is path-independent between finds), but NOT for the
# reference's stale accounting: an own block popped by an intermediate
# adoption and later re-included via a third branch (a >=triple-race
# geometry) is counted stale by the reference's per-arrival reorgs
# (simulation.h:129-135) yet invisible to a single batched reorg. Restoring
# exactness needs one notify round per distinct pending-arrival time, whose
# SIMD batch-max cost erases the halved step count. Verified empirically by
# tests/test_state_equivalence.py on the heterogeneous-propagation stream
# (seed 13, run 2: stale 3 vs 2). Event stepping stays.


class Engine:
    """Chunked batch executor for one SimConfig.

    This object owns the jitted per-chunk programs; :meth:`run_batch` is the
    TPU replacement for the reference's thread fan-out (main.cpp:205-213):
    runs become a vectorized leading axis instead of std::async tasks, and
    with a device mesh the runs axis is sharded via shard_map with final
    psum-reduced statistics (collectives ride ICI instead of a shared-memory
    join, SURVEY.md section 2.2).
    """

    def __init__(
        self, config: SimConfig, mesh: Mesh | None = None, *,
        packed: bool = False,
    ):
        """``packed`` (tpusim.packed — device-side grid packing) makes the
        scenario parameters per-RUN runtime tensors: every ``SimParams``
        leaf gains a leading runs axis (stacked by the packed dispatcher),
        the per-run duration ledger initializes from :attr:`run_durations`,
        and :meth:`run_batch` returns RAW per-run leaves (no batch-global
        host reduction) so the dispatcher can segment-reduce them per grid
        point. The per-run compute is identical — vmap slices each run the
        same params it would have received broadcast — so results are
        bit-equal to a sequential per-point sweep (pinned by
        tests/test_packed_sweep.py). Both generators pack: threefry keys
        and xoroshiro per-run stream rows are per-run leading-axis inputs
        either way (``make_keys``), and for xoroshiro the stacked
        ``mean_interval_ms`` leaf is float64 so the packed interval mapping
        matches the sequential Python-float broadcast bit-for-bit under
        JAX_ENABLE_X64 (tpusim.packed.stack_params). Packed engines run
        unsharded (mesh packing rides the next-TPU-window checklist with
        the rest of SPMD)."""
        if packed:
            if mesh is not None:
                raise ValueError(
                    "packed engines run unsharded; mesh grid packing rides "
                    "the next TPU window (ROADMAP)"
                )
        self.packed = packed
        #: Per-run int64 duration_ms array (packed mode only; None keeps the
        #: config-scalar ledger). Set by the packed dispatcher BEFORE the
        #: first dispatch of each packed batch — a runtime input like keys.
        self.run_durations: np.ndarray | None = None
        self.config = config
        self.mesh = mesh
        # Fault-injection seam (tpusim.chaos): host-side only, never traced —
        # a None injector costs one `is not None` per batch and leaves the
        # compiled programs byte-identical to a chaos-less build (pinned by
        # tests/test_chaos.py).
        self.chaos = None
        #: Wall-clock watchdog for the pipelined done-flag fetch; None (the
        #: default) keeps the fetch a plain transfer with zero extra
        #: machinery. Set (seconds) to detect a wedged tunnel mid-pipeline:
        #: an overdue fetch raises PipelineStallError, which run_batch
        #: degrades to a synchronous re-dispatch of the batch.
        self.flag_fetch_timeout_s: float | None = None
        self.params = make_params(config)
        self.n_miners = config.network.n_miners
        self.exact = config.resolved_mode == "exact"
        self.any_selfish = config.network.any_selfish
        bound = default_n_steps(config.duration_ms, config.network.block_interval_s)
        # The chunk budget is sampling identity, so its resolution lives in
        # ONE jax-free place — SimConfig.resolved_chunk_steps (sizing
        # rationale there) — shared with the packed shape key that groups
        # grid points without building an engine.
        self.chunk_steps = config.resolved_chunk_steps
        # Host-loop safety margin: generous vs the per-run 8-sigma bound
        # because the loop must cover the batch *max* event count; the second
        # term covers runs that freeze at TIME_CAP and re-base repeatedly.
        self.max_chunks = (
            (bound + 4 * self.chunk_steps) // self.chunk_steps
            + config.duration_ms // int(TIME_CAP)
            + 4
        )

        # Superstep width: K events unrolled per lax.scan step. The scan
        # carry round-trip (the whole state tree) is paid once per K events
        # instead of per event, and the draws are untouched — event e of a
        # chunk still consumes word pair e of the chunk's threefry block, so
        # results are bit-identical across K (pinned by
        # tests/test_superstep.py).
        self.superstep = resolve_superstep(
            config.superstep, self.chunk_steps, exact=self.exact
        )

        m, k, exact, steps = (
            self.n_miners, config.resolved_group_slots, self.exact, self.chunk_steps
        )
        any_selfish = self.any_selfish
        K = self.superstep
        # Packed-state count dtype (int16 when the duration-derived bound
        # provably fits — config.resolved_count_dtype) and the batched-RNG
        # toggle: both pure compile-time knobs, results bit-identical.
        from .state import COUNT_DTYPES

        self.count_dtype = cdt = COUNT_DTYPES[config.resolved_count_dtype]
        rng_batch = config.rng_batch
        # Miner-axis gather reads + per-chunk count re-basing: both pure
        # compile-time knobs, results bit-identical (the A/B twins of
        # rng_batch — tests/test_consensus_gather.py pins both).
        gather = config.consensus_gather
        self.count_rebase = count_rebase = config.count_rebase
        # Flight recorder (tpusim.flight): a trace-time constant. 0 means the
        # recorder leaves are never created and no recording op is traced —
        # the jitted programs are identical to a recorder-less build (pinned
        # by tests/test_flight.py).
        self.flight_capacity = fcap = config.flight_capacity
        if fcap:
            from . import flight as _flight

        xoro = config.rng == "xoroshiro"

        if xoro:
            from .state import INTERVAL_CAP
            from .xoroshiro import (
                interval_ms_from_word,
                next_words,
                next_words_wide,
                select_stream_by_count,
                unpack_run_streams,
                winners_from_words64,
            )

            def init_fn(packed: jax.Array, params: SimParams):
                state = init_state(m, k, exact, cdt, any_selfish, count_rebase)
                xi, xw = unpack_run_streams(packed)
                # Initial next-block draw from the interval stream, like the
                # native loop's pre-loop draw (simcore simulate_run).
                xi, ih, il = next_words(xi)
                nbt = interval_ms_from_word(
                    ih, il, params.mean_interval_ms, float(INTERVAL_CAP)
                )
                # The recorder and count-base slots are always present; None
                # is an empty pytree, so the fcap=0 / un-rebased aux (and
                # every program carrying it) is unchanged by the uniform
                # arity.
                fr = _flight.init_recorder(fcap) if fcap else None
                cb = jnp.zeros((m,), jnp.int32) if count_rebase else None
                return state._replace(next_block_time=nbt), (
                    init_counters(m), xi, xw, fr, cb,
                )

            def chunk_fn(
                state: SimState, aux, cap: jax.Array, run_key: jax.Array,
                chunk_idx: jax.Array, params: SimParams,
            ):
                ctr, xi, xw, fr, cb = aux

                def body_wide(carry, _):
                    # Batched wide generation (rng_batch): pre-advance both
                    # sequential streams K words, map ALL K candidate
                    # (winner, interval) pairs in one vectorized pass, and
                    # let each unrolled event select its draw by consumption
                    # count — word c goes to the c-th CONSUMED draw, exactly
                    # the conditional-advance order of the per-event path
                    # (and of the native backend), so results stay
                    # bit-compatible. The final stream state is the
                    # consumed-count-th lookahead state.
                    st, xi, xw, ctr, fr = carry
                    wstates, wh, wl = next_words_wide(xw, K)
                    istates, ih, il = next_words_wide(xi, K)
                    w_cand = winners_from_words64(
                        wh, wl, params.thr64_hi, params.thr64_lo
                    )
                    dt_cand = interval_ms_from_word(
                        ih, il, params.mean_interval_ms, float(INTERVAL_CAP)
                    )
                    consumed = jnp.zeros((), jnp.int32)
                    kidx = jnp.arange(K)
                    for _j in range(K):
                        prev = st
                        found_due = (st.t < cap) & (st.t == st.next_block_time)
                        sel = kidx == consumed
                        w = jnp.sum(jnp.where(sel, w_cand, 0), dtype=jnp.int32)
                        dt = jnp.sum(jnp.where(sel, dt_cand, 0), dtype=jnp.int32)
                        st, fr = _step_event(
                            st, w, dt, params, cap, any_selfish, fr=fr,
                            gather=gather,
                        )
                        consumed = consumed + found_due.astype(jnp.int32)
                        ctr = _count_step(ctr, prev, st, cap)
                    xi = select_stream_by_count(consumed, xi, istates)
                    xw = select_stream_by_count(consumed, xw, wstates)
                    return (st, xi, xw, ctr, fr), None

                def body_seq(carry, _):
                    st, xi, xw, ctr, fr = carry
                    for _j in range(K):
                        prev = st
                        st, xi, xw, fr = _step_xoro(
                            st, xi, xw, params, cap, any_selfish, fr,
                            gather=gather,
                        )
                        ctr = _count_step(ctr, prev, st, cap)
                    return (st, xi, xw, ctr, fr), None

                (state, xi, xw, ctr, fr), _ = jax.lax.scan(
                    body_wide if rng_batch else body_seq,
                    (state, xi, xw, ctr, fr), None, length=steps // K,
                )
                state, elapsed = rebase(state)
                if fr is not None:
                    fr = _flight.advance_base(fr, elapsed)
                if count_rebase:
                    state, cb, fr = apply_count_rebase(state, cb, fr)
                return state, (ctr, xi, xw, fr, cb), elapsed
        else:
            from .sampling import winners_from_bits

            def init_fn(run_key: jax.Array, params: SimParams):
                state = init_state(m, k, exact, cdt, any_selfish, count_rebase)
                bits = jax.random.bits(jax.random.fold_in(run_key, 0), (2,), jnp.uint32)
                # None recorder/count-base slots = empty pytree leaves: see
                # the xoroshiro twin.
                fr = _flight.init_recorder(fcap) if fcap else None
                cb = jnp.zeros((m,), jnp.int32) if count_rebase else None
                return state._replace(
                    next_block_time=interval_from_bits(bits[1], params.mean_interval_ms)
                ), (init_counters(m), fr, cb)

            def chunk_fn(
                state: SimState, aux, cap: jax.Array, run_key: jax.Array,
                chunk_idx: jax.Array, params: SimParams,
            ):
                ctr, fr, cb = aux
                key = jax.random.fold_in(run_key, 1 + chunk_idx)
                # The (steps, 2) word block reshaped to (steps/K, K, ...):
                # scan step s row j is word pair s*K + j — the same per-event
                # mapping as K=1, just consumed K events at a time.
                bits = jax.random.bits(key, (steps, 2), jnp.uint32)
                if rng_batch:
                    # Batched wide generation (rng_batch): the whole chunk's
                    # sampler output — winner index and interval ms — is
                    # mapped from the word block in ONE vectorized pass (the
                    # tfp.mcmc discipline of vectorizing the sampler), so
                    # the serial event loop consumes precomputed draws
                    # instead of re-deriving them per event. Same words,
                    # same elementwise maps: bit-identical to the per-event
                    # path.
                    w_all = winners_from_bits(bits[:, 0], params.thresholds)
                    dt_all = interval_from_bits(bits[:, 1], params.mean_interval_ms)
                    xs = (
                        w_all.reshape(steps // K, K),
                        dt_all.reshape(steps // K, K),
                    )

                    def body(carry, x):
                        st, ctr, fr = carry
                        wk, dtk = x
                        for j in range(K):
                            prev = st
                            st, fr = _step_event(
                                st, wk[j], dtk[j], params, cap, any_selfish,
                                fr=fr, gather=gather,
                            )
                            ctr = _count_step(ctr, prev, st, cap)
                        return (st, ctr, fr), None

                else:
                    xs = bits.reshape(steps // K, K, 2)

                    def body(carry, x):
                        st, ctr, fr = carry
                        for j in range(K):
                            prev = st
                            st, fr = _step(st, x[j], params, cap, any_selfish,
                                           fr, gather=gather)
                            ctr = _count_step(ctr, prev, st, cap)
                        return (st, ctr, fr), None

                (state, ctr, fr), _ = jax.lax.scan(body, (state, ctr, fr), xs)
                state, elapsed = rebase(state)
                if fr is not None:
                    fr = _flight.advance_base(fr, elapsed)
                if count_rebase:
                    state, cb, fr = apply_count_rebase(state, cb, fr)
                return state, (ctr, fr, cb), elapsed

        def finalize_fn(
            state: SimState, t_end: jax.Array, cbase=None
        ) -> dict[str, jax.Array]:
            # ``cbase`` is the aux's accumulated per-run count base (int32
            # [R, M] under count_rebase, None otherwise): final_stats is the
            # re-add boundary where the re-based counts become absolute
            # again, so every output below is bit-identical either way.
            per_run = jax.vmap(final_stats)(state, t_end, cbase)
            if packed:
                # Packed grids: NOTHING is reduced over the runs axis on
                # device — a batch mixes grid points, so every leaf keeps
                # its runs (= segment) axis and the dispatcher reduces per
                # point on the host with the exact reductions the
                # sequential path applies per batch (tpusim.packed).
                return {
                    "blocks_found_per_run": per_run["blocks_found"],
                    "stale_blocks_per_run": per_run["stale_blocks"],
                    "best_height_per_run": per_run["best_height"],
                    "overflow_per_run": per_run["overflow"],
                    "blocks_share_per_run": per_run["blocks_share"],
                    "stale_rate_per_run": per_run["stale_rate"],
                }
            return {
                "blocks_found_sum": jnp.sum(per_run["blocks_found"], axis=0),
                "stale_blocks_sum": jnp.sum(per_run["stale_blocks"], axis=0),
                "best_height_sum": jnp.sum(per_run["best_height"]),
                "overflow_sum": jnp.sum(per_run["overflow"]),
                # The per-run float32 ratios leave the device unsummed: an
                # 8192-element float32 device sum put ~1e-5 absolute noise on
                # the share/stale-rate means (one order under the ±1e-4
                # cross-validation criterion); _host_reduce_sums sums them in
                # float64 on the host instead, for ~(R, M) float32 of extra
                # transfer per batch (~0.3 MB at the default batch size).
                "blocks_share_per_run": per_run["blocks_share"],
                "stale_rate_per_run": per_run["stale_rate"],
                # Per-run found counts feed ONLY the streaming-moment
                # telemetry (second moments need per-run values; the stat
                # path keeps the exact device int sum above). Same transfer
                # budget class as the two ratio leaves.
                "blocks_found_per_run": per_run["blocks_found"],
            }

        # Packed grids vmap the params leaves over the runs axis (each run
        # carries its grid point's roster/interval); broadcast otherwise.
        pax = 0 if packed else None
        vinit = jax.vmap(init_fn, in_axes=(0, pax))
        vchunk = jax.vmap(chunk_fn, in_axes=(0, 0, 0, 0, None, pax))
        self._init_impl = vinit
        self._chunk_impl = vchunk
        self._finalize_impl = finalize_fn

        if mesh is None:
            self._init = jax.jit(vinit)
            self._chunk = jax.jit(vchunk)
            self._finalize = jax.jit(finalize_fn)
            self._run_device = jax.jit(self._device_loop)
            # Pipelined per-chunk program: state, aux and the ledger pair are
            # donated — each chunk writes into its predecessor's buffers —
            # and the only host-fetched value per chunk is the int32
            # unfinished flag, so the host can run several chunks ahead of
            # the device (see _run_batch_pipelined).
            self._pipe_chunk = jax.jit(self._ledger_chunk, donate_argnums=(0, 1, 2, 3))
        else:
            # check_vma off: scan carries start as unvarying constants but
            # become varying over the sharded runs axis after the first step.
            rep_params = jax.tree_util.tree_map(lambda _: P(), self.params)
            self._init = jax.jit(
                shard_map(
                    vinit, mesh=mesh,
                    in_specs=(P("runs"), rep_params),
                    out_specs=(P("runs"), P("runs")),
                    check_vma=False,
                )
            )
            self._chunk = jax.jit(
                shard_map(
                    vchunk, mesh=mesh,
                    in_specs=(P("runs"), P("runs"), P("runs"), P("runs"), P(), rep_params),
                    out_specs=(P("runs"), P("runs"), P("runs")),
                    check_vma=False,
                )
            )

            # Multi-controller runs cannot gather per-run leaves to one host
            # (non-addressable shards), so they reduce the ratio sums on
            # device in float32 as psums — the historical behavior. Single-
            # controller meshes keep the per-run leaves sharded and let the
            # host do the float64 sum, identical to the no-mesh path.
            multiproc = jax.process_count() > 1
            out_specs = {
                "blocks_found_sum": P(), "stale_blocks_sum": P(),
                "best_height_sum": P(), "overflow_sum": P(),
            }
            if multiproc:
                out_specs.update(blocks_share_sum=P(), stale_rate_sum=P())
            else:
                out_specs.update(
                    blocks_share_per_run=P("runs"), stale_rate_per_run=P("runs"),
                    blocks_found_per_run=P("runs"),
                )

            def sharded_finalize(state, t_end, cbase):
                local = finalize_fn(state, t_end, cbase)
                share = local.pop("blocks_share_per_run")
                stale = local.pop("stale_rate_per_run")
                found = local.pop("blocks_found_per_run")
                out = jax.tree_util.tree_map(lambda x: jax.lax.psum(x, "runs"), local)
                if multiproc:
                    # Non-addressable shards cannot reach the host moment
                    # reduction, so multi-controller runs emit no streaming-
                    # moment keys (same policy as the flight ring): the found
                    # per-run leaf is dropped with them.
                    out["blocks_share_sum"] = jax.lax.psum(jnp.sum(share, axis=0), "runs")
                    out["stale_rate_sum"] = jax.lax.psum(jnp.sum(stale, axis=0), "runs")
                else:
                    out["blocks_share_per_run"] = share
                    out["stale_rate_per_run"] = stale
                    out["blocks_found_per_run"] = found
                return out

            self._finalize = jax.jit(
                shard_map(
                    sharded_finalize, mesh=mesh,
                    in_specs=(P("runs"), P("runs"), P("runs")),
                    out_specs=out_specs,
                    check_vma=False,
                )
            )

            if not multiproc:
                # Single-controller mesh: the whole device-resident batch
                # loop runs SPMD — each device drives its own lax.while_loop
                # over its run shard (no collectives inside, so divergent
                # chunk counts are fine) and only the final stat sums meet in
                # a psum. This is what puts the >1-device path on the same
                # one-dispatch-per-batch footing as the single-device path
                # (and, for PallasEngine, the kernel on every device).
                loop_out_specs = {
                    "blocks_found_sum": P(), "stale_blocks_sum": P(),
                    "best_height_sum": P(), "overflow_sum": P(),
                    "blocks_share_per_run": P("runs"),
                    "stale_rate_per_run": P("runs"),
                    "blocks_found_per_run": P("runs"),
                    "tele_reorg_depth_per_run": P("runs"),
                    "tele_stale_events_per_run": P("runs"),
                    "tele_active_steps_per_run": P("runs"),
                    "tele_stale_by_miner_per_run": P("runs"),
                    "tele_reorg_depth_hist_per_run": P("runs"),
                    "n_chunks": P(), "unfinished": P(),
                }
                if self.flight_capacity:
                    loop_out_specs["flight_buf"] = P("runs")
                    loop_out_specs["flight_count"] = P("runs")

                def sharded_device_loop(keys, hi0, lo0, params):
                    sums = self._device_loop(keys, hi0, lo0, params)
                    out = {}
                    for name, v in sums.items():
                        if name.endswith("_per_run") or name.startswith("flight_"):
                            out[name] = v
                        elif name == "n_chunks":
                            out[name] = jax.lax.pmax(v, "runs")
                        elif name == "unfinished":
                            out[name] = jax.lax.pmax(v.astype(jnp.int32), "runs")
                        else:
                            out[name] = jax.lax.psum(v, "runs")
                    return out

                self._run_device = jax.jit(
                    shard_map(
                        sharded_device_loop, mesh=mesh,
                        in_specs=(P("runs"), P("runs"), P("runs"), rep_params),
                        out_specs=loop_out_specs,
                        check_vma=False,
                    )
                )

                def sharded_ledger_chunk(state, aux, hi, lo, keys, chunk_idx, params):
                    out = self._ledger_chunk(state, aux, hi, lo, keys, chunk_idx, params)
                    # The done decision must be global: every shard returns
                    # the mesh-wide max of its local unfinished flag.
                    return out[:-1] + (jax.lax.pmax(out[-1], "runs"),)

                self._pipe_chunk = jax.jit(
                    shard_map(
                        sharded_ledger_chunk, mesh=mesh,
                        in_specs=(
                            P("runs"), P("runs"), P("runs"), P("runs"),
                            P("runs"), P(), rep_params,
                        ),
                        out_specs=(P("runs"), P("runs"), P("runs"), P("runs"), P()),
                        check_vma=False,
                    ),
                    donate_argnums=(0, 1, 2, 3),
                )

    def reuse_key(self) -> tuple:
        """Hashable identity of every value BAKED into this engine's jitted
        programs — two configs with equal keys compile to the same programs,
        so one Engine can serve both (the roster percentages, propagation
        delays and seed are runtime inputs via ``params``/``keys`` and stay
        out of the key). Used by the sweep driver's engine cache
        (tpusim.runner.make_engine) to stop same-shape grid points from
        recompiling per point."""
        c = self.config
        mesh_id = None
        if self.mesh is not None:
            # Topology identity: shard-mapped programs bake the mesh's axis
            # layout and device set.
            mesh_id = (
                self.mesh.axis_names, self.mesh.devices.shape,
                tuple(d.id for d in self.mesh.devices.flat),
            )
        return (
            type(self).__name__, self.n_miners, c.resolved_group_slots,
            self.exact, self.any_selfish, self.chunk_steps, self.superstep,
            self.max_chunks, c.rng, c.flight_capacity, c.rng_batch,
            c.resolved_count_dtype, c.consensus_gather, c.count_rebase,
            self.packed, mesh_id,
        )

    def rebind(self, config: SimConfig, key: tuple) -> "Engine":
        """Point this engine at another config whose freshly-constructed
        engine produced :meth:`reuse_key` ``key`` (the cache caller builds
        that candidate anyway — construction is cheap, compilation is not):
        only the runtime inputs — roster params, seed, duration ledger —
        change, so every compiled program stays valid and warm."""
        if key != self.reuse_key():
            raise ValueError(
                f"rebind across engine shapes: {key} != {self.reuse_key()}"
            )
        self.config = config
        self.params = make_params(config)
        return self

    def memory_attrs(self) -> dict[str, int]:
        """Static memory model of this engine's compiled programs, merged
        into every ``batch`` telemetry span: the dtype-resolved per-run
        state footprint (the same number the roofline traffic model calls
        ``state`` — packed int16 leaves halve it). :class:`PallasEngine`
        extends this with its kernel VMEM estimate against the scoped-VMEM
        budget, so the ledger shows headroom, not just usage."""
        from .profiling import state_bytes_per_run

        return {"state_bytes_per_run": int(state_bytes_per_run(self))}

    def make_keys(self, start: int, count: int) -> jax.Array:
        """The per-run sampling-identity array for global run indices
        [start, start+count) — threefry keys by default, packed xoroshiro
        stream limbs for rng="xoroshiro". Opaque to callers: whatever this
        returns is what :meth:`run_batch` expects as ``keys``."""
        if self.config.rng == "xoroshiro":
            from .xoroshiro import pack_run_streams

            return jnp.asarray(pack_run_streams(self.config.seed, start, count))
        from .runner import make_run_keys

        return make_run_keys(self.config.seed, start, count)

    # Base for the on-device remaining-time ledger: remaining = hi * 2^30 + lo.
    # A chunk's elapsed is < TIME_CAP + INTERVAL_CAP + max prop < 2^30 (one
    # event can overshoot the cap), so one borrow per chunk suffices and the
    # final (possibly negative) t_end fits a single int32 limb.
    _LEDGER_BASE = 1 << 30

    def _ledger_init(self, n: int) -> tuple[jax.Array, jax.Array]:
        """Split ``duration_ms`` into the per-run (hi, lo) int32 ledger pair.
        The ledger was per-run from the start, so ragged packed horizons
        (``run_durations``) cost nothing: each run simply starts with its own
        remaining-time budget and freezes when it runs out — the "duration
        mask" of the packed dispatcher is this pair."""
        shift = self._LEDGER_BASE.bit_length() - 1
        mask = self._LEDGER_BASE - 1
        if self.run_durations is not None:
            dur = np.asarray(self.run_durations, dtype=np.int64)
            if dur.shape != (n,):
                raise ValueError(
                    f"run_durations shape {dur.shape} != batch ({n},)"
                )
            return (
                jnp.asarray((dur >> shift).astype(np.int32)),
                jnp.asarray((dur & mask).astype(np.int32)),
            )
        dur = int(self.config.duration_ms)
        hi = jnp.full((n,), dur >> shift, jnp.int32)
        lo = jnp.full((n,), dur & mask, jnp.int32)
        return hi, lo

    def _device_loop(self, keys: jax.Array, hi0: jax.Array, lo0: jax.Array,
                     params: SimParams) -> dict[str, jax.Array]:
        """The whole batch — init, every chunk, finalize — as ONE jitted
        program: ``lax.while_loop`` over chunks with the int64 remaining-time
        ledger carried as a base-2^30 int32 pair on device.

        This is the single-device hot path. The per-chunk host loop of
        :meth:`_run_batch_hostloop` costs one dispatch + host sync per chunk
        (~90 chunks for a year-long batch), which on a tunneled TPU dominates
        end-to-end time by an order of magnitude; here the host pays one
        dispatch and one transfer of the final stat sums per batch.
        """
        state, aux = self._init_impl(keys, params)
        base = jnp.int32(self._LEDGER_BASE)
        tc = jnp.int32(int(TIME_CAP))
        limit = jnp.int32(self.max_chunks)

        def cond(carry):
            i, _, _, hi, lo = carry
            return (i < limit) & jnp.any((hi > 0) | (lo > 0))

        def body(carry):
            i, state, aux, hi, lo = carry
            cap = jnp.maximum(jnp.where(hi > 0, tc, jnp.minimum(lo, tc)), 0)
            state, aux, elapsed = self._chunk_impl(
                state, aux, cap, keys, i.astype(jnp.uint32), params
            )
            lo = lo - elapsed
            borrow = (lo < 0) & (hi > 0)
            hi = jnp.where(borrow, hi - 1, hi)
            lo = jnp.where(borrow, lo + base, lo)
            return i + 1, state, aux, hi, lo

        i, state, aux, hi, lo = jax.lax.while_loop(
            cond, body, (jnp.int32(0), state, aux, hi0, lo0)
        )
        sums = self._finalize_impl(state, hi * base + lo, aux[-1])
        # Per-run telemetry counters out of the carried aux; reduced on the
        # host like the ratio leaves (_host_reduce_telemetry) — an int32
        # device sum of active_steps would overflow on large batches.
        self._aux_to_sums(aux, sums)
        sums["n_chunks"] = i
        sums["unfinished"] = jnp.any((hi > 0) | (lo > 0))
        return sums

    def _aux_to_sums(self, aux, sums: dict) -> None:
        """Spill the carried aux (counters and, when recording, the flight
        ring) into per-run output leaves — the one place the aux layout is
        decoded, shared by all three dispatch paths. The aux tuple always
        ends (..., fr, cb): recorder slot then accumulated count base, each
        None (an empty pytree leaf) when its feature is off; ``cb`` is
        consumed by finalize's re-add, not exported."""
        ctr: SimCounters = aux[0]
        sums["tele_reorg_depth_per_run"] = ctr.reorg_max
        sums["tele_stale_events_per_run"] = ctr.stale_events
        sums["tele_active_steps_per_run"] = ctr.active_steps
        sums["tele_stale_by_miner_per_run"] = ctr.stale_by_miner
        sums["tele_reorg_depth_hist_per_run"] = ctr.reorg_depth_hist
        if self.flight_capacity:
            fr = aux[-2]
            sums["flight_buf"] = fr.buf
            sums["flight_count"] = fr.count

    def _ledger_chunk(self, state, aux, hi, lo, keys, chunk_idx, params):
        """One chunk of :meth:`_device_loop`'s body as a standalone jitted
        step: cap from the device-resident ledger, run the chunk, subtract
        elapsed with one borrow, and reduce the all-runs-done decision to a
        single int32 ``unfinished`` flag — the only value the pipelined host
        loop ever fetches. A finished batch's extra chunks are exact no-ops
        (cap=0 freezes every run and rebase of an all-zero clock elapses 0)."""
        base = jnp.int32(self._LEDGER_BASE)
        tc = jnp.int32(int(TIME_CAP))
        cap = jnp.maximum(jnp.where(hi > 0, tc, jnp.minimum(lo, tc)), 0)
        state, aux, elapsed = self._chunk_impl(state, aux, cap, keys, chunk_idx, params)
        lo = lo - elapsed
        borrow = (lo < 0) & (hi > 0)
        hi = jnp.where(borrow, hi - 1, hi)
        lo = jnp.where(borrow, lo + base, lo)
        unfinished = jnp.any((hi > 0) | (lo > 0)).astype(jnp.int32)
        return state, aux, hi, lo, unfinished

    _PIPELINE_DEPTH = 2

    def _run_batch_pipelined(self, keys: jax.Array) -> dict[str, np.ndarray]:
        """Per-chunk dispatch loop that never blocks on the chunk it just
        dispatched: the ledger lives on device as the (hi, lo) int32 pair,
        state/aux/ledger buffers are donated chunk-to-chunk, and the host
        checks chunk c's ``unfinished`` flag only after dispatching chunks
        c+1..c+depth — so the host-side Python/dispatch work (and everything
        the caller does between batches) overlaps device compute instead of
        serializing with it. Draw-for-draw identical to the device loop and
        the host loop: same chunk program, same cap rule, same ledger
        arithmetic."""
        from collections import deque

        n = keys.shape[0]
        hi, lo = self._ledger_init(n)
        state, aux = self._init(keys, self.params)
        flags: deque = deque()
        # Chunks popped until (and including) the first all-done flag = the
        # busy-chunk count of the device loop; the overshoot chunks the
        # pipeline dispatched behind it are exact no-ops and stay uncounted,
        # so tele_chunks_max is dispatch-path-invariant.
        popped = 0
        finished = False
        for chunk_idx in range(self.max_chunks):
            state, aux, hi, lo, unfin = self._pipe_chunk(
                state, aux, hi, lo, keys, jnp.asarray(chunk_idx, jnp.uint32), self.params
            )
            flags.append(unfin)
            if len(flags) > self._PIPELINE_DEPTH:
                popped += 1
                # The ONE sanctioned sync of the pipelined loop: this flag's
                # chunk was dispatched depth chunks ago, so the fetch only
                # blocks when the host is already ahead (and _fetch_flag's
                # watchdog bounds how long "blocks" may mean).
                if self._fetch_flag(flags.popleft()) == 0:
                    finished = True
                    break
        while not finished and flags:
            popped += 1
            # Drain after the last dispatch; the device is the critical path
            # here by construction.
            finished = self._fetch_flag(flags.popleft()) == 0
        if not finished:
            raise RuntimeError(
                f"batch did not finish within {self.max_chunks} chunks of "
                f"{self.chunk_steps} steps — event count beyond the Poisson bound"
            )
        t_end = hi * jnp.int32(self._LEDGER_BASE) + lo
        sums = self._finalize(state, t_end, aux[-1])
        # tpusim-lint: disable=JX002 -- batch-end stat transfer, once per
        # batch, after the dispatch loop has fully drained.
        out = {k: np.asarray(v) for k, v in sums.items()}
        if not self.packed:
            out = _host_reduce_sums(out)
        dev_sums: dict = {}
        self._aux_to_sums(aux, dev_sums)
        # tpusim-lint: disable=JX002 -- same batch-end transfer as above: the
        # aux counters (and flight ring, if recording) come down once per
        # batch, after the dispatch loop has fully drained.
        out.update({k: np.asarray(v) for k, v in dev_sums.items()})
        if self.packed:
            # Raw per-run leaves: the packed dispatcher segment-reduces them
            # per grid point; only the busy-chunk count is batch-scoped.
            out["tele_chunks_max"] = np.int64(popped)
        else:
            _host_reduce_telemetry(out, popped)
        out["runs"] = np.int64(n)
        return out

    def _fetch_flag(self, flag) -> int:
        """Fetch one pipelined done-flag, through the chaos seam and (when
        ``flag_fetch_timeout_s`` is set) the wall-clock watchdog. Both
        failure shapes — an injected hang and a genuinely overdue transfer —
        surface as :class:`tpusim.chaos.PipelineStallError`, the signal
        :meth:`run_batch` degrades on."""
        if self.chaos is not None:
            try:
                self.chaos.fire("pipeline.flag_fetch")
            except InjectedHang as e:
                raise PipelineStallError(str(e)) from None
        if self.flag_fetch_timeout_s is not None:
            return fetch_with_deadline(
                lambda: int(flag), self.flag_fetch_timeout_s,
                what="pipelined done-flag fetch",
            )
        # tpusim-lint: disable=JX002 -- the sanctioned pipelined-loop sync;
        # see the call sites in _run_batch_pipelined.
        return int(flag)

    def _fire_dispatch(self, n: int) -> None:
        """The engine-level chaos seam: fires once per batch dispatch, on
        whichever entry path the batch takes (async device loop, pipelined,
        host loop)."""
        if self.chaos is not None:
            self.chaos.fire(
                "engine.run_batch", engine=type(self).__name__, runs=n
            )

    def _batch_guard(self, n: int) -> None:
        if self.run_durations is not None:
            # Packed batch: per-run durations and per-run mean intervals —
            # the bound is the sum of each run's expected block count.
            mi = np.asarray(self.params.mean_interval_ms, dtype=np.float64)
            dur = np.asarray(self.run_durations, dtype=np.float64)
            blocks_bound = float(np.sum(dur / np.maximum(mi, 1.0))) * 1.1
            if blocks_bound > _I32_SUM_GUARD:
                raise ValueError(
                    f"packed batch of {n} runs overflows int32 block-count "
                    f"sums ({blocks_bound:.3g} expected blocks); lower the "
                    f"pack width"
                )
            return
        duration = self.config.duration_ms
        blocks_bound = n * (duration / (self.config.network.block_interval_s * 1000.0)) * 1.1
        if blocks_bound > _I32_SUM_GUARD:
            raise ValueError(
                f"batch of {n} runs x {duration} ms overflows int32 block-count "
                f"sums; lower batch_size below {int(_I32_SUM_GUARD / (blocks_bound / n))}"
            )

    def _device_loop_ok(self, n: int) -> bool:
        return self.mesh is None or (
            jax.process_count() == 1 and n % self.mesh.devices.size == 0
        )

    def run_batch(
        self, keys: jax.Array, *, host_loop: bool = False, pipelined: bool = False
    ) -> dict[str, np.ndarray]:
        """Simulate one batch of runs to completion; returns stat sums.

        Single-device and single-controller meshes: one jitted
        device-resident program per batch (:meth:`_device_loop`, shard-mapped
        over the mesh when there is one), or — with ``pipelined=True`` — the
        per-chunk pipelined dispatch loop of :meth:`_run_batch_pipelined`.
        Multi-controller meshes (or ``host_loop=True``, kept for
        device/host-loop equivalence tests): jitted chunk -> re-base ->
        subtract elapsed from the int64 remaining ledger on the host ->
        repeat until every run finishes. All paths draw identically and
        produce bit-identical sums.
        """
        n = keys.shape[0]
        self._batch_guard(n)
        if self._device_loop_ok(n) and not host_loop:
            if pipelined:
                self._fire_dispatch(n)
                try:
                    return self._run_batch_pipelined(keys)
                except PipelineStallError as e:
                    # Watchdog degradation: a wedged done-flag fetch must not
                    # hang the run. The pipelined loop's buffers were donated
                    # chunk-to-chunk but `keys` was not, so the batch can be
                    # re-dispatched from scratch synchronously — same draws,
                    # bit-identical sums, one batch of lost work.
                    logger.warning(
                        "pipelined dispatch stalled (%s); re-running the "
                        "batch synchronously", e,
                    )
                    return self.run_batch_async(keys)()
            return self.run_batch_async(keys)()
        self._fire_dispatch(n)
        return self._run_batch_hostloop(keys)

    def run_batch_async(self, keys: jax.Array):
        """Dispatch one whole batch (the device-resident loop) and return a
        zero-argument finalize callable; the device computes in the
        background until the callable is invoked, which blocks on the
        transfer, validates the chunk-limit flag and returns the stat sums.
        This is the batch-level pipelining hook: dispatch batch c+1 before
        finalizing batch c and the host-side reduction/bookkeeping of c
        overlaps c+1's device time. Falls back to a synchronous host-loop
        run (wrapped in a trivial callable) when the device loop is not
        eligible."""
        n = keys.shape[0]
        self._batch_guard(n)
        self._fire_dispatch(n)
        if not self._device_loop_ok(n):
            out = self._run_batch_hostloop(keys)
            return lambda: out
        hi0, lo0 = self._ledger_init(n)
        sums = self._run_device(keys, hi0, lo0, self.params)

        def finalize() -> dict[str, np.ndarray]:
            # tpusim-lint: disable=JX002 -- THE deliberate sync point: the
            # whole contract of run_batch_async is that this callable blocks.
            out = {k: np.asarray(v) for k, v in sums.items()}
            if not self.packed:
                out = _host_reduce_sums(out)
            n_chunks = int(out.pop("n_chunks"))
            if out.pop("unfinished"):
                raise RuntimeError(
                    f"batch did not finish within {n_chunks} chunks of "
                    f"{self.chunk_steps} steps (limit {self.max_chunks}) — "
                    f"event count beyond the Poisson bound"
                )
            # n_chunks is already the busy-chunk count: the while cond admits
            # only chunks with >= 1 unfinished run (pmax across mesh shards).
            if self.packed:
                out["tele_chunks_max"] = np.int64(n_chunks)
            else:
                _host_reduce_telemetry(out, n_chunks)
            out["runs"] = np.int64(n)
            return out

        return finalize

    def _run_batch_hostloop(self, keys: jax.Array) -> dict[str, np.ndarray]:
        """Per-chunk host loop (see :meth:`run_batch`).

        The ledger is int64 HOST numpy by design (a year is 3.2e10 ms, past
        int32, and TPUs have no fast int64); under multi-controller JAX the
        batch arrays have non-addressable shards, so the ledger holds real
        values only at this process's run indices, device inputs (cap, t_end)
        are assembled shard-by-shard, and loop termination is agreed globally
        — every process must keep calling the SPMD chunk program until ALL
        runs everywhere finish, with its own finished runs frozen by cap=0.
        """
        n = keys.shape[0]
        duration = self.config.duration_ms
        multiproc = self.mesh is not None and jax.process_count() > 1
        if multiproc:
            from jax.experimental import multihost_utils
            from jax.sharding import NamedSharding, PartitionSpec

            sharding = NamedSharding(self.mesh, PartitionSpec("runs"))

            def device_i32(host_arr: np.ndarray) -> jax.Array:
                return jax.make_array_from_callback(
                    (n,), sharding, lambda index: host_arr[index].astype(np.int32)
                )

            local_mask = np.zeros((n,), dtype=bool)
            for dev, index in sharding.devices_indices_map((n,)).items():
                if dev.process_index == jax.process_index():
                    local_mask[index] = True

            def ledger_update(remaining: np.ndarray, elapsed: jax.Array) -> None:
                for shard in elapsed.addressable_shards:
                    # tpusim-lint: disable=JX002 -- the host loop IS the
                    # per-chunk-sync dispatch path (kept for multi-controller
                    # meshes and equivalence tests; the pipelined/device-loop
                    # paths exist to avoid exactly this transfer).
                    remaining[shard.index] -= np.asarray(shard.data, dtype=np.int64)

            def all_done(remaining: np.ndarray) -> bool:
                local = bool(np.all(remaining[local_mask] <= 0))
                return bool(np.all(multihost_utils.process_allgather(np.array([local]))))
        else:
            device_i32 = lambda host_arr: jnp.asarray(host_arr.astype(np.int32))
            def ledger_update(remaining: np.ndarray, elapsed: jax.Array) -> None:
                remaining -= np.asarray(elapsed, dtype=np.int64)
            all_done = lambda remaining: bool(np.all(remaining <= 0))

        state, aux = self._init(keys, self.params)
        # Multi-process: non-local entries stay at `duration` forever (their
        # processes own them); only local indices are read or updated.
        if self.run_durations is not None:
            remaining = np.asarray(self.run_durations, dtype=np.int64).copy()
            if remaining.shape != (n,):
                raise ValueError(
                    f"run_durations shape {remaining.shape} != batch ({n},)"
                )
        else:
            remaining = np.full((n,), duration, dtype=np.int64)
        time_cap = np.int64(int(TIME_CAP))

        for chunk_idx in range(self.max_chunks):
            cap = device_i32(np.minimum(np.maximum(remaining, 0), time_cap))
            state, aux, elapsed = self._chunk(
                state, aux, cap, keys, jnp.asarray(chunk_idx, jnp.uint32), self.params
            )
            ledger_update(remaining, elapsed)
            if all_done(remaining):
                break
        else:
            raise RuntimeError(
                f"batch did not finish within {self.max_chunks} chunks of "
                f"{self.chunk_steps} steps — event count beyond the Poisson bound"
            )

        t_end = device_i32(remaining)
        sums = self._finalize(state, t_end, aux[-1])
        # tpusim-lint: disable=JX002 -- batch-end stat transfer (see
        # _run_batch_pipelined); the loop above has already terminated.
        out = {k: np.asarray(v) for k, v in sums.items()}
        if not self.packed:
            out = _host_reduce_sums(out)
        if multiproc:
            # Non-addressable shards: telemetry reduces over this process's
            # local runs only (the stat sums above are still global psums).
            # Run-axis concatenation, not ravel: the histogram counter leaves
            # are (runs, M)/(runs, B) shaped.
            # tpusim-lint: disable=JX002 -- once per batch, after the loop.
            fetch = lambda arr: np.concatenate(
                [np.asarray(s.data) for s in arr.addressable_shards], axis=0
            )
        else:
            fetch = np.asarray
        dev_sums: dict = {}
        self._aux_to_sums(aux, dev_sums)
        if multiproc:
            # Shard order is not run order, so per-run flight rows cannot be
            # attributed to global run indices here; recording stays a
            # single-controller affair (the trace CLI never shards).
            dev_sums.pop("flight_buf", None)
            dev_sums.pop("flight_count", None)
        out.update({k: fetch(v) for k, v in dev_sums.items()})
        # Every executed chunk had >= 1 active run (the loop breaks the
        # moment all_done flips), so chunk_idx + 1 IS the busy-chunk count.
        if self.packed:
            out["tele_chunks_max"] = np.int64(chunk_idx + 1)
        else:
            _host_reduce_telemetry(out, chunk_idx + 1)
        out["runs"] = np.int64(n)
        return out
