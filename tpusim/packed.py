"""Device-side grid packing: run a whole sweep grid as ONE device program.

``run_sweep`` holds one compiled engine across same-shape grid points
(Engine.rebind, tests/test_sweep_engine_cache.py) but still dispatches the
points *sequentially* — the device idles between points and every point pays
a full dispatch round trip. This module packs an entire grid onto the runs
axis of one compiled program instead (the accelerator-saturation trick of
batched policy simulation, PAPERS.md arXiv:2406.01939, and the Ising-on-TPU
recipe of one program over a lattice of configurations, arXiv:1903.11714):

  * **Per-run scenario params.** Every ``SimParams`` leaf gains a leading
    runs axis (:func:`stack_params`): roster thresholds, propagation delays,
    selfish flags and the mean block interval become runtime tensors, vmapped
    per run by ``Engine(packed=True)``. Ragged horizons need no mask at all —
    the engines' remaining-time ledger was per-run from the start, so each
    run simply carries its own point's ``duration_ms``.
  * **Shape agreement.** Points pack together exactly when they would compile
    the same program (:func:`pack_shape_key` — a jax-free conservative twin
    of ``Engine.reuse_key``): same miner count, mode, resolved chunk budget,
    rng and compile-time knobs. Points that disagree form separate packs —
    ``rng="xoroshiro"`` and flight-recorder grids pack too, each in their
    own shape group (README "Grid packing"): xoroshiro runs carry per-run
    stream rows (xoroshiro.pack_run_streams — the global-run-index
    derivation the native backend uses, so the packed word-consumption
    order stays byte-diffable via ``tpusim trace diff``), and flight rings
    are runs-axis leaves decoded per piece
    (flight_export.decode_flight_packed).
  * **Per-point checkpoints mid-pack.** ``checkpoint_dir`` writes the SAME
    fingerprinted per-point npz the sequential runner writes
    (runner.checkpoint_fingerprint), sliced from the raw per-run leaves at
    piece (= batch) boundaries after every dispatch — so a killed packed
    dispatch resumes bit-equal to an uninterrupted one, packed and
    sequential checkpoints are mutually resumable, and a fleet packed
    sub-grid unit heals mid-pack instead of restarting the sub-grid.
  * **Per-run -> per-point segment reduction.** A packed engine returns RAW
    per-run leaves (``combine_sums`` concatenates them across any split);
    :func:`_fold_piece` applies, per grid point, byte-for-byte the host
    reductions the sequential path applies per batch — device-exact integer
    sums, float64 ratio folds over the same values in the same order, the
    exact int64 moment keys of tpusim.convergence, and the SimCounters
    reductions — so every per-point output is BIT-equal to the sequential
    sweep (pinned by tests/test_packed_sweep.py). Pieces are cut at each
    point's own ``batch_size`` boundaries so even the float64 accumulation
    order matches a sequential run.
  * **int16 safety under packing.** A packed batch mixes rosters, so the
    packed state dtype resolves from the WORST-CASE point
    (:func:`packed_count_dtype`: max ``count_bound`` over the pack) — int16
    only when every point provably fits, loud ``ValueError`` when a point
    explicitly demands int16 the pack cannot honor.
  * **Adaptive runs-per-point.** :func:`run_grid_adaptive` drives the
    ``ci_target_stat`` convergence machinery inside the packed batch: each
    round re-allocates the fixed lane budget toward the points with the
    widest relative CI (converged points stop consuming lanes), at constant
    dispatch width so the whole loop stays on one compiled program.

Module import is jax-free (the fleet supervisor groups sub-grids with
:func:`pack_shape_key` without initializing a backend); only the dispatch
functions import the engines lazily.
"""

from __future__ import annotations

import dataclasses
import logging
import time
from typing import Any, Iterable

import numpy as np

from .config import SimConfig
from .convergence import STATS as MOMENT_STATS
from .convergence import MomentAccumulator, moment_keys
from .provenance import (
    checkpoint_address,
    checkpoint_content,
    emit_lineage,
    lineage_armed,
)
from .stats import SimResults

logger = logging.getLogger("tpusim")

__all__ = [
    "pack_shape_key",
    "packable",
    "packed_count_dtype",
    "plan_packs",
    "stack_params",
    "run_grid",
    "run_grid_adaptive",
]


def _resolved_chunk_steps(cfg: SimConfig) -> int:
    """The sampling-identity chunk budget, single-sourced (jax-free) in
    ``SimConfig.resolved_chunk_steps`` — ``Engine.__init__`` assigns from
    the same property, and tests/test_packed_sweep.py pins the agreement
    against engine drift."""
    return cfg.resolved_chunk_steps


def pack_shape_key(cfg: SimConfig) -> tuple:
    """Hashable program-shape identity for grid packing, jax-free: two
    configs with equal keys trace the same packed program (params and
    durations are runtime inputs), so they may share one pack. Conservative
    refinement of ``Engine.reuse_key``: it additionally pins the resolved
    chunk budget (part of the sampling identity — packing must not change
    any point's draws) but leaves the roster, interval, seed and duration
    out (those are exactly what packing turns into runtime tensors). The
    count dtype is deliberately NOT in the key: the pack resolves it from
    the worst-case point (:func:`packed_count_dtype`)."""
    return (
        cfg.network.n_miners,
        cfg.resolved_group_slots,
        cfg.resolved_mode,
        cfg.network.any_selfish,
        cfg.rng,
        cfg.flight_capacity,
        cfg.rng_batch,
        cfg.consensus_gather,
        cfg.count_rebase,
        cfg.superstep,
        _resolved_chunk_steps(cfg),
    )


def packable(cfg: SimConfig) -> bool:
    """Whether this point may enter a pack at all. Always True since the
    packed-path completion: xoroshiro points pack with per-run stream rows,
    flight-recorder points pack with per-piece ring decode, and
    checkpointed grids slice piece checkpoints mid-pack — each forms its
    own shape group via :func:`pack_shape_key`. The remaining carve-outs
    (device meshes / multi-controller, README "Grid packing") are
    environment properties, not config ones, and are enforced where the
    mesh exists (``Engine(packed=True)`` rejects a mesh). Kept as a seam so
    any future per-config restriction lands in one place."""
    return True


def packed_count_dtype(configs: Iterable[SimConfig]) -> str:
    """The packed state dtype for one pack, resolved from the WORST-CASE
    point: int16 only when the max ``count_bound`` over the pack fits (each
    run only ever holds its own point's dynamics, so the per-point bounds
    apply per run — but the COMPILED layout is shared, so one over-bound
    point widens the whole pack). Explicit ``state_dtype`` requests are
    honored fail-loud: "int32" anywhere forces int32; "int16" anywhere that
    the worst case cannot honor raises instead of silently widening."""
    configs = list(configs)
    worst = max(c.count_bound for c in configs)
    fits = worst <= 2**15 - 1
    explicit16 = [c for c in configs if c.state_dtype == "int16"]
    if any(c.state_dtype == "int32" for c in configs):
        if explicit16:
            raise ValueError(
                "pack mixes explicit state_dtype='int16' and 'int32' points; "
                "packed state is one shared layout — align the knobs or "
                "run sequentially"
            )
        return "int32"
    if explicit16 and not fits:
        raise ValueError(
            f"state_dtype='int16' requested but the pack's worst-case "
            f"count_bound ({worst}) exceeds int16; a packed batch shares one "
            f"state layout, so the widest point decides — use 'auto' (the "
            f"pack widens to int32) or pack that point separately"
        )
    return "int16" if fits else "int32"


@dataclasses.dataclass
class _Pack:
    """One shape-agreement group: the points (original indices kept for
    output ordering) that run as one compiled device program."""

    key: tuple
    indices: list[int]


def plan_packs(
    points: list[tuple[str, SimConfig]]
) -> tuple[list[_Pack], list[int]]:
    """Partition a grid into packs by shape agreement. Returns
    ``(packs, sequential)`` — ``sequential`` lists the indices of points
    that cannot pack (:func:`packable`) and must take the per-point path.
    jax-free: the fleet supervisor plans sub-grids with this."""
    packs: dict[tuple, _Pack] = {}
    sequential: list[int] = []
    for i, (_, cfg) in enumerate(points):
        if not packable(cfg):
            sequential.append(i)
            continue
        key = pack_shape_key(cfg)
        pack = packs.get(key)
        if pack is None:
            packs[key] = pack = _Pack(key=key, indices=[])
        pack.indices.append(i)
    return list(packs.values()), sequential


# ---------------------------------------------------------------------------
# Packed params + dispatch (lazy jax from here down).


def stack_params(configs: list[SimConfig], counts: list[int]):
    """One ``SimParams`` whose every leaf carries a leading runs axis:
    config ``i``'s params repeated ``counts[i]`` times. The per-run values
    are exactly what the sequential engine would have broadcast, so the
    vmapped compute is bit-identical per run."""
    import jax.numpy as jnp

    from .state import make_params

    per = [make_params(c) for c in configs]
    reps = np.asarray(counts)

    def stack(leaves):
        arr = np.stack([np.asarray(v) for v in leaves])
        return jnp.asarray(np.repeat(arr, reps, axis=0))

    # threefry: float32 per-run scalar — every consumer casts to f32 anyway
    # (sampling.interval_from_bits), so the value each run sees is
    # bit-identical to the sequential engine's Python-float broadcast.
    # xoroshiro: float64 — the interval mapping
    # (xoroshiro.interval_ms_from_word) multiplies the mean in f64 under
    # JAX_ENABLE_X64 (the native-A/B contract), and an f32 leaf would round
    # `mean * 1e6` differently from the sequential Python-float product;
    # without x64 jnp downcasts the leaf to f32, matching the sequential
    # cast. Uniform per pack: rng is in pack_shape_key.
    mean_dtype = (
        np.float64 if configs[0].rng == "xoroshiro" else np.float32
    )
    mean = np.repeat(
        np.asarray([p.mean_interval_ms for p in per], dtype=mean_dtype), reps
    )
    from .state import SimParams

    return SimParams(
        thresholds=stack([p.thresholds for p in per]),
        prop_ms=stack([p.prop_ms for p in per]),
        selfish=stack([p.selfish for p in per]),
        mean_interval_ms=jnp.asarray(mean),
        thr64_hi=stack([p.thr64_hi for p in per]),
        thr64_lo=stack([p.thr64_lo for p in per]),
    )


@dataclasses.dataclass
class _Piece:
    """A contiguous slice of one point's runs inside a packed dispatch. Cut
    at the point's own ``batch_size`` boundaries, so the per-point host
    accumulation order matches the sequential runner's exactly (float64
    sums are order-sensitive; integer/moment sums are not)."""

    point: int  # index into the pack's member list
    start: int  # run offset within the point (the sampling-identity index)
    count: int


def _point_pieces(cfg: SimConfig, start: int = 0) -> list[tuple[int, int]]:
    """Piece layout of one point's runs from global run index ``start``
    (nonzero on checkpoint resume — batches are cut from ``runs_done``
    forward, NOT re-aligned to absolute boundaries, exactly the sequential
    runner's resume semantics so the float64 fold order matches a resumed
    sequential sweep too)."""
    batch = max(1, min(cfg.batch_size, cfg.runs))
    return [
        (s, min(batch, cfg.runs - s))
        for s in range(start, cfg.runs, batch)
    ]


def _zero_point_sums(n_miners: int) -> dict[str, Any]:
    return {
        "blocks_found_sum": np.zeros(n_miners, np.int64),
        "stale_blocks_sum": np.zeros(n_miners, np.int64),
        "best_height_sum": np.int64(0),
        "overflow_sum": np.int64(0),
        "blocks_share_sum": np.zeros(n_miners, np.float64),
        "stale_rate_sum": np.zeros(n_miners, np.float64),
        "runs": np.int64(0),
    }


def _zero_point_tele(n_miners: int) -> dict[str, Any]:
    from .engine import DEPTH_BUCKETS

    return {
        "reorg_depth_max": 0,
        "stale_events": 0,
        "active_steps": 0,
        "stale_by_miner": np.zeros(n_miners, np.int64),
        "reorg_depth_hist": np.zeros(DEPTH_BUCKETS, np.int64),
    }


def _fold_piece(
    state: dict[str, Any], raw: dict[str, np.ndarray], sl: slice
) -> None:
    """Fold one piece's slice of a packed dispatch's raw per-run leaves into
    a point's accumulators — byte-for-byte the reductions the sequential
    path applies per batch (engine._host_reduce_sums /
    _host_reduce_telemetry + the runner's int64/float64 accumulation), just
    applied to the segment instead of the whole batch. Integer sums are
    exact in any order; the float64 ratio folds see the same values in the
    same order as the sequential batch (pieces are batch-boundary cuts), so
    the per-point results are bit-equal."""
    sums = state["sums"]
    found = raw["blocks_found_per_run"][sl]
    share = raw["blocks_share_per_run"][sl]
    stale_rate = raw["stale_rate_per_run"][sl]
    sums["blocks_found_sum"] = sums["blocks_found_sum"] + found.sum(
        axis=0, dtype=np.int64
    )
    sums["stale_blocks_sum"] = sums["stale_blocks_sum"] + raw[
        "stale_blocks_per_run"
    ][sl].sum(axis=0, dtype=np.int64)
    sums["best_height_sum"] = sums["best_height_sum"] + raw[
        "best_height_per_run"
    ][sl].sum(dtype=np.int64)
    sums["overflow_sum"] = sums["overflow_sum"] + raw["overflow_per_run"][
        sl
    ].sum(dtype=np.int64)
    # The float64 host fold of the sequential path (_host_reduce_sums):
    # same dtype ladder, same axis, same element order.
    sums["blocks_share_sum"] = sums["blocks_share_sum"] + share.astype(
        np.float64
    ).sum(axis=0)
    sums["stale_rate_sum"] = sums["stale_rate_sum"] + stale_rate.astype(
        np.float64
    ).sum(axis=0)
    sums["runs"] = sums["runs"] + np.int64(found.shape[0])

    # Exact int64 moment keys (tpusim.convergence) per piece, folded by the
    # accumulator exactly as the runner folds per-batch keys.
    per = {"blocks_found": found, "blocks_share": share,
           "stale_rate": stale_rate}
    assert len(per) == len(MOMENT_STATS)
    state["moments"].add(moment_keys(per))

    tele = state["tele"]
    tele["reorg_depth_max"] = max(
        tele["reorg_depth_max"],
        int(raw["tele_reorg_depth_per_run"][sl].max(initial=0)),
    )
    tele["stale_events"] += int(
        raw["tele_stale_events_per_run"][sl].astype(np.int64).sum()
    )
    tele["active_steps"] += int(
        raw["tele_active_steps_per_run"][sl].astype(np.int64).sum()
    )
    tele["stale_by_miner"] = tele["stale_by_miner"] + raw[
        "tele_stale_by_miner_per_run"
    ][sl].astype(np.int64).sum(axis=0)
    tele["reorg_depth_hist"] = tele["reorg_depth_hist"] + raw[
        "tele_reorg_depth_hist_per_run"
    ][sl].astype(np.int64).sum(axis=0)


def _make_packed_engine(
    configs: list[SimConfig],
    *,
    engine: str = "auto",
    engine_cache: dict | None = None,
    pack_width: int | None = None,
    pallas_kwargs: dict | None = None,
):
    """Build (or fetch from ``engine_cache``) the packed engine for one
    pack: a representative config pinned to the pack's resolved chunk budget
    and worst-case count dtype, duration set to the pack max so the chunk
    limit covers every member."""
    import jax

    from .engine import Engine

    dtype = packed_count_dtype(configs)
    cs = _resolved_chunk_steps(configs[0])
    max_dur = max(c.duration_ms for c in configs)
    # Engine.max_chunks derives from default_n_steps(duration, interval),
    # and a pack may MIX block intervals (the 4096 clamp makes short-
    # interval chunk budgets coincide in pack_shape_key) — so the
    # representative takes the worst-event-bound member's network: with the
    # pack-max duration on top its bound dominates every member's own, or a
    # shorter-interval member would exhaust the chunk loop ("batch did not
    # finish"). The interval itself is a runtime tensor like the roster.
    worst = max(configs, key=lambda c: c._event_bound(c.duration_ms))
    # Resolve with "auto" first: pinning "int16" here would make
    # SimConfig.__post_init__ raise inside dataclasses.replace whenever the
    # synthetic representative (worst roster x the pack-max duration)
    # exceeds the members' own bounds, before the widening check could run.
    rep = dataclasses.replace(
        configs[0], network=worst.network, duration_ms=max_dur,
        chunk_steps=cs, state_dtype="auto",
        runs=max(c.runs for c in configs),
    )
    if dtype == "int16" and rep._count_bound_fits_int16:
        rep = dataclasses.replace(rep, state_dtype="int16")
    else:
        # dtype is not part of the sampling identity, so widening the
        # representative is always safe — just less packed.
        rep = dataclasses.replace(rep, state_dtype="int32")

    def build():
        # tpusim-lint: disable=JX001 -- `engine` is the host-side string knob
        # ("auto"/"scan"/"pallas"), never a tracer; build() runs pre-trace.
        if engine == "pallas" or (
            engine == "auto"
            and jax.devices()[0].platform == "tpu"
            and jax.process_count() == 1
        ):
            try:
                from .pallas_engine import PallasEngine

                return PallasEngine(rep, packed=True, **(pallas_kwargs or {}))
            except ValueError:
                if engine == "pallas":
                    raise
                logger.info(
                    "pack not eligible for the pallas engine; using scan"
                )
        return Engine(rep, packed=True)

    if engine_cache is None:
        return build()
    key = ("packed", engine, pack_shape_key(rep), rep.resolved_count_dtype,
           rep.duration_ms, pack_width,
           tuple(sorted((pallas_kwargs or {}).items())))
    eng = engine_cache.get(key)
    if eng is None:
        engine_cache[key] = eng = build()
    return eng


def _pad_width(width: int, eng) -> int:
    """Round a dispatch width up to the engine's alignment unit (the pallas
    run tile; 1 for the scan engine)."""
    unit = getattr(eng, "tile_runs", 1)
    return (width + unit - 1) // unit * unit


#: Lazily-jitted whole-batch key builder (see _batch_run_keys).
_KEYS_FN = None

#: Host cache of ``jax.random.key(seed)``'s raw uint32 key data, per seed.
#: Bounded by the distinct seeds a process ever packs (grids share one seed
#: per config, typically one per grid).
_BASE_KEY_DATA: dict[int, np.ndarray] = {}


def _base_key_data(seed: int) -> np.ndarray:
    """Raw key data of ``jax.random.key(seed)`` — the SAME host construction
    the sequential ``runner.make_run_keys`` starts from, so every seed the
    sequential path accepts produces identical draws packed. (A direct
    ``np.uint32(seed)`` cast would diverge: jax wraps out-of-range Python
    ints where numpy 2.x raises.)"""
    kd = _BASE_KEY_DATA.get(seed)
    if kd is None:
        import jax

        kd = np.asarray(jax.random.key_data(jax.random.key(seed)))
        _BASE_KEY_DATA[seed] = kd
    return kd


def _batch_run_keys(key_data: np.ndarray, idx: np.ndarray):
    """All pieces' run keys in ONE jitted call — bit-identical to per-piece
    ``runner.make_run_keys`` (``fold_in(key(seed), i)`` per run; pinned by
    the packed-vs-sequential row equality), but without its per-call eager
    dispatch cost: at reference grid shapes the per-piece key builds were
    ~40% of the packed dispatch wall time. ``key_data`` is the per-run
    ``(n, 2)`` uint32 base-key array (:func:`_base_key_data` per config)."""
    global _KEYS_FN
    import jax
    import jax.numpy as jnp

    if _KEYS_FN is None:
        def build(kd, idx):
            keys = jax.random.wrap_key_data(kd)
            return jax.vmap(jax.random.fold_in)(keys, idx)

        _KEYS_FN = jax.jit(build)
    return _KEYS_FN(jnp.asarray(key_data), jnp.asarray(idx))


def _dispatch(
    eng,
    members: list[SimConfig],
    pieces: list[_Piece],
    width: int,
    *,
    host_loop: bool = False,
    pipelined: bool = False,
    params_cache: dict | None = None,
):
    """Run one packed dispatch of ``pieces`` (padded to ``width`` runs) and
    return the raw per-run leaves. Pad lanes carry duration 0 — they freeze
    at step one and cost (almost) nothing — and are never sliced by any
    piece. ``params_cache`` (keyed by the dispatch's exact (config, count)
    layout — SimConfig is frozen, hence hashable) skips re-stacking and
    re-uploading the per-run params when the same layout dispatches again:
    a repeated grid or an adaptive loop at stable allocation pays the
    host->device params transfer once."""
    total = sum(p.count for p in pieces)
    npad = width - total
    assert npad >= 0, (width, total)
    cfgs = [members[p.point] for p in pieces]
    counts = [p.count for p in pieces]
    xoro = members[0].rng == "xoroshiro"  # uniform per pack (pack_shape_key)
    durations = np.repeat(
        np.asarray([c.duration_ms for c in cfgs], np.int64), counts
    )
    if xoro:
        # Per-run stream rows from each piece's GLOBAL run indices — the
        # native backend's own derivation (xoroshiro.engine_run_seeds), so
        # the packed word-consumption order per run is byte-identical to a
        # sequential dispatch and to `tpusim trace --backend cpp`.
        from .xoroshiro import pack_run_streams

        streams = [
            pack_run_streams(c.seed, p.start, p.count)
            for c, p in zip(cfgs, pieces)
        ]
    else:
        key_data = np.repeat(
            np.stack([_base_key_data(c.seed) for c in cfgs]), counts, axis=0
        )
        idx = np.concatenate(
            [np.arange(p.start, p.start + p.count) for p in pieces]
        )
    if npad:
        cfgs = cfgs + [cfgs[0]]
        counts = counts + [npad]
        durations = np.concatenate([durations, np.zeros(npad, np.int64)])
        if xoro:
            streams.append(pack_run_streams(0, 0, npad))
        else:
            key_data = np.concatenate(
                [key_data, np.repeat(_base_key_data(0)[None], npad, axis=0)]
            )
            idx = np.concatenate([idx, np.arange(npad)])
    layout = ("packed_params", tuple(cfgs), tuple(counts))
    params = params_cache.get(layout) if params_cache is not None else None
    if params is None:
        params = stack_params(cfgs, counts)
        if params_cache is not None:
            params_cache[layout] = params
    eng.params = params
    eng.run_durations = durations
    if xoro:
        import jax.numpy as jnp

        keys = jnp.asarray(np.concatenate(streams))
    else:
        keys = _batch_run_keys(key_data, idx)
    raw = eng.run_batch(keys, host_loop=host_loop, pipelined=pipelined)
    return raw


def run_grid(
    points: list[tuple[str, SimConfig]],
    *,
    engine: str = "auto",
    engine_cache: dict | None = None,
    pack_width: int | None = None,
    host_loop: bool = False,
    pipelined: bool = False,
    telemetry=None,
    chaos=None,
    checkpoint_dir=None,
    pallas_kwargs: dict | None = None,
    progress=None,
) -> list[dict[str, Any]]:
    """Run every point of one shape-agreement pack as packed device
    dispatches; returns one result dict per point, in input order:
    ``{"name", "results": SimResults, "sums", "moments", "tele",
    "elapsed_s"}`` (plus ``"flight"``: a decoded
    :class:`~tpusim.flight_export.FlightLog` when the pack records flight
    events). ``points`` must all share one :func:`pack_shape_key`
    (``run_sweep(packed=True)`` plans the partition; this function trusts
    it). ``pack_width`` fixes the dispatch width (defaults to the largest
    member ``batch_size``, clamped to the grid total) — every dispatch of a
    multi-dispatch grid is padded to it, so the whole grid compiles ONE
    program and a second same-width grid compiles nothing
    (compile_count_guard(exact=0), tests/test_packed_sweep.py).
    ``checkpoint_dir`` arms per-point piece checkpoints: after every
    dispatch each touched point's accumulated sums are saved to
    ``<dir>/<name>.npz`` in the sequential runner's fingerprinted format
    (runner.checkpoint_fingerprint), and points with a matching checkpoint
    resume from their saved run index — bit-equal to an uninterrupted run,
    and interchangeable with the sequential path's checkpoints (moments and
    flight events stay session-scoped across a resume, like the sequential
    runner's). ``progress(done_runs, total_runs)`` fires after every
    dispatch with grid-cumulative counts (a resumed grid starts at its
    checkpointed base) — the runner's per-batch callback contract, so a
    fleet worker's heartbeat can carry packed progress too."""
    members = [cfg for _, cfg in points]
    names = [name for name, _ in points]
    if not members:
        return []
    keyset = {pack_shape_key(c) for c in members}
    if len(keyset) != 1:
        raise ValueError(
            f"run_grid needs one shape-agreement pack, got {len(keyset)} "
            f"distinct shapes; plan with plan_packs/run_sweep(packed=True)"
        )

    t0 = time.monotonic()
    # Compile observability for the packed path (the runner arms this for
    # sequential dispatches; packed grids never enter the runner): every XLA
    # compile a packed grid pays lands as a `compile` span in the same
    # ledger — which is also what lets the fleet timeline (tpusim.tracing)
    # attribute a packed worker's first-dispatch wall-clock to compile
    # instead of lumping it into dispatch.
    compile_ledger = None
    if telemetry is not None:
        from .telemetry import CompileLedger

        compile_ledger = CompileLedger(telemetry).install()
        compile_ledger.set_context(dispatch="packed_grid")
    try:
        eng = _make_packed_engine(
            members, engine=engine, engine_cache=engine_cache,
            pack_width=pack_width, pallas_kwargs=pallas_kwargs,
        )
        eng.chaos = chaos
        if compile_ledger is not None:
            compile_ledger.set_context(engine=type(eng).__name__)
        return _run_grid_dispatches(
            eng, members, names, pack_width=pack_width,
            host_loop=host_loop, pipelined=pipelined,
            engine_cache=engine_cache, telemetry=telemetry,
            chaos=chaos, checkpoint_dir=checkpoint_dir,
            progress=progress, t0=t0,
        )
    finally:
        if compile_ledger is not None:
            compile_ledger.uninstall()


def _run_grid_dispatches(
    eng, members, names, *, pack_width, host_loop, pipelined,
    engine_cache, telemetry, progress, t0, chaos=None, checkpoint_dir=None,
) -> list[dict[str, Any]]:
    m = members[0].network.n_miners
    flight = members[0].flight_capacity > 0  # uniform per pack (shape key)

    state = [
        {"sums": _zero_point_sums(m), "moments": MomentAccumulator(),
         "tele": _zero_point_tele(m)}
        for _ in members
    ]
    if flight:
        from .flight_export import FlightLog

        for i, cfg in enumerate(members):
            state[i]["flight"] = FlightLog(
                events=[], dropped={}, capacity=cfg.flight_capacity
            )

    # Per-point piece checkpoints: the sequential runner's own fingerprinted
    # npz (same filename convention as run_sweep's sequential path), loaded
    # before piecing so a resumed point's remaining batches are cut from its
    # saved run index forward — exactly the sequential resume semantics.
    ckpts: list = [None] * len(members)
    done = [0] * len(members)
    if checkpoint_dir is not None:
        from pathlib import Path

        from .runner import _Checkpoint, checkpoint_fingerprint

        ckdir = Path(checkpoint_dir)
        ckdir.mkdir(parents=True, exist_ok=True)
        for i, cfg in enumerate(members):
            ck = _Checkpoint(
                ckdir / f"{names[i]}.npz",
                checkpoint_fingerprint(cfg, _resolved_chunk_steps(cfg)),
                chaos=chaos,
            )
            ckpts[i] = ck
            t_ld = time.perf_counter()
            loaded = ck.load()
            if loaded is None:
                continue
            runs_loaded, saved = loaded
            done[i] = min(int(runs_loaded), cfg.runs)
            sums = state[i]["sums"]
            for k in sums:
                # Fold onto the zero template (keeps the int64/float64
                # accumulator dtypes) — schema equality with the sequential
                # checkpoint is pinned by the lint contract and tests.
                sums[k] = sums[k] + saved[k]
            logger.info(
                "resuming packed point %s from checkpoint at %d/%d runs",
                names[i], done[i], cfg.runs,
            )
            if lineage_armed():
                # Load-side attestation first (the runner discipline): a kill
                # inside the saving process's ckpt.save leaves the checkpoint
                # durable but unrecorded; the loader re-attests the same
                # deterministic content address so the cite always resolves.
                ck_addr = emit_lineage(
                    "checkpoint",
                    content=checkpoint_content(ck.fingerprint, done[i]),
                    config_fingerprint=ck.fingerprint, runs_done=done[i],
                    path=str(ck.path), point=names[i], attested="load",
                )
                # key= files the load under the point name, so the row
                # sweep.emit_row eventually emits for this point cites the
                # checkpoint it healed from — the packed path has no per-run
                # "run" record to chain through.
                emit_lineage(
                    "checkpoint_load",
                    parents=(ck_addr
                             or checkpoint_address(ck.fingerprint, done[i]),),
                    config_fingerprint=ck.fingerprint, runs_done=done[i],
                    path=str(ck.path), point=names[i], packed=True,
                    key=names[i],
                )
            if telemetry is not None:
                dur_ld = time.perf_counter() - t_ld
                telemetry.emit(
                    "checkpoint_load", t_start=time.time() - dur_ld,
                    dur_s=dur_ld, runs_done=done[i], path=str(ck.path),
                    point=names[i], packed=True,
                )

    # Pieces in point order, cut at each point's own batch boundaries (from
    # its resumed run index forward, matching a resumed sequential sweep).
    pieces: list[_Piece] = []
    for i, cfg in enumerate(members):
        pieces.extend(_Piece(i, s, c) for s, c in _point_pieces(cfg, done[i]))
    total = sum(c.runs for c in members)
    runs_done = sum(done)
    dispatches: list[list[_Piece]] = []
    width = 0
    if pieces:
        width = pack_width or min(
            sum(p.count for p in pieces), max(c.batch_size for c in members)
        )
        width = max(width, max(p.count for p in pieces))
        width = _pad_width(
            min(width, sum(p.count for p in pieces))
            if pack_width is None else width, eng,
        )

        # Greedy fill: consecutive pieces until the width is reached. Every
        # dispatch is padded to the shared width so the compiled program is
        # one.
        dispatches.append([])
        fill = 0
        for p in pieces:
            if fill + p.count > width and dispatches[-1]:
                dispatches.append([])
                fill = 0
            dispatches[-1].append(p)
            fill += p.count

    for di, batch in enumerate(dispatches):
        t_d = time.monotonic()
        raw = _dispatch(
            eng, members, batch, width,
            host_loop=host_loop, pipelined=pipelined,
            params_cache=engine_cache,
        )
        off = 0
        for p in batch:
            _fold_piece(state[p.point], raw, slice(off, off + p.count))
            done[p.point] += p.count
            off += p.count
        if flight:
            from .flight_export import decode_flight_packed

            logs = decode_flight_packed(
                {"flight_buf": raw["flight_buf"],
                 "flight_count": raw["flight_count"]},
                [(p.point, p.start, p.count) for p in batch],
            )
            for pt, log in logs.items():
                state[pt]["flight"].extend(log)
        runs_done += sum(p.count for p in batch)
        if checkpoint_dir is not None:
            # Save every point the dispatch touched — the packed twin of the
            # runner's per-batch save, so a kill between dispatches loses at
            # most one dispatch of work per point.
            for pt in sorted({p.point for p in batch}):
                t_ck = time.perf_counter()
                ckpts[pt].save(done[pt], state[pt]["sums"])
                if lineage_armed():
                    emit_lineage(
                        "checkpoint",
                        content=checkpoint_content(
                            ckpts[pt].fingerprint, done[pt]
                        ),
                        config_fingerprint=ckpts[pt].fingerprint,
                        runs_done=done[pt], path=str(ckpts[pt].path),
                        point=names[pt],
                    )
                if telemetry is not None:
                    dur_ck = time.perf_counter() - t_ck
                    telemetry.emit(
                        "checkpoint_save", t_start=time.time() - dur_ck,
                        dur_s=dur_ck, runs_done=done[pt],
                        path=str(ckpts[pt].path), point=names[pt],
                        packed=True,
                    )
        if progress is not None:
            progress(runs_done, total)
        if telemetry is not None:
            dur_d = time.monotonic() - t_d
            telemetry.emit(
                # Backdated start: the default t_start would stamp the END
                # and misplace the interval on the raw wall axis.
                "packed_dispatch", t_start=time.time() - dur_d,
                dur_s=round(dur_d, 6),
                dispatch=di, dispatches=len(dispatches), width=width,
                runs=sum(p.count for p in batch), pieces=len(batch),
                points=len({p.point for p in batch}),
                engine=type(eng).__name__,
                chunks=int(raw.get("tele_chunks_max", 0)),
            )

    # Per-point wall-clock: the pack ran as one program, so the only honest
    # per-point attribution is the pack's elapsed AMORTIZED over its members
    # — summing member rows then recovers the true wall-clock instead of
    # over-counting it N-fold (sweep_point span durations stay additive).
    elapsed = (time.monotonic() - t0) / len(members)
    out = []
    for i, (name, cfg) in enumerate(zip(names, members)):
        st = state[i]
        res = SimResults.from_sums(
            st["sums"], cfg, mode=cfg.resolved_mode,
            elapsed_s=round(elapsed, 6),
        )
        if telemetry is not None:
            # Segment-aware stats span: one per point, `point` names the
            # segment — `tpusim watch`/`report` render these as the
            # per-point convergence table instead of one blended run.
            telemetry.emit(
                "stats", point=name, runs=st["moments"].n,
                runs_done=st["moments"].n, runs_total=cfg.runs,
                duration_ms=cfg.duration_ms,
                block_interval_s=cfg.network.block_interval_s,
                packed=True,
                stats=st["moments"].snapshot(),
            )
        row = {
            "name": name, "results": res, "sums": st["sums"],
            "moments": st["moments"], "tele": st["tele"],
            "elapsed_s": elapsed,
        }
        if flight:
            st["flight"].events.sort(key=lambda e: (e["run"], e["seq"]))
            row["flight"] = st["flight"]
        out.append(row)
    return out


def _allocate_lanes(
    active: list[int],
    need: dict[int, float],
    remaining: dict[int, int],
    lanes: int,
    min_runs: int,
) -> dict[int, int]:
    """Split ``lanes`` runs across ``active`` points proportionally to
    ``need``, each clamped to its ``remaining`` budget and floored at
    ``min_runs``. Integer-rounding overshoot is trimmed from the
    smallest-need points first (the widest-CI point keeps its share) but
    NEVER below the ``min_runs`` floor: callers guarantee
    ``len(active) * min_runs <= lanes``, so once every point sits at the
    floor the total already fits and the trim loop has terminated."""
    total_need = sum(need[i] for i in active)
    alloc = {
        i: min(
            remaining[i],
            max(min_runs, int(round(lanes * need[i] / total_need))),
        )
        for i in active
    }
    while sum(alloc.values()) > lanes:
        i = min(
            (i for i in active if alloc[i] > min_runs),
            key=lambda i: need[i], default=None,
        )
        if i is None:
            break
        alloc[i] -= 1
    return alloc


def run_grid_adaptive(
    points: list[tuple[str, SimConfig]],
    *,
    ci_target_stat: str,
    ci_target_rel: float = 0.01,
    lanes: int | None = None,
    max_rounds: int = 32,
    min_runs: int = 2,
    engine: str = "auto",
    engine_cache: dict | None = None,
    telemetry=None,
    chaos=None,
    quiet: bool = True,
) -> list[dict[str, Any]]:
    """Run-until-confident over a packed grid: the ``ci_target_stat``
    convergence driver (the runner's adaptive-precision machinery) deciding
    *runs per point* inside the packed batch. Every round dispatches one
    packed batch of ``lanes`` runs; unconverged points split the lanes in
    proportion to their estimated remaining need (``n * (rel/target)^2 - n``
    — the 1/sqrt(n) extrapolation of tpusim.convergence), so wide-CI points
    get more lanes next round and converged points stop consuming any. The
    dispatch width is CONSTANT (padded), so the whole loop runs on one
    compiled program. Each point's runs extend its sequential sampling
    identity (run index continues where the last round stopped), and
    ``config.runs`` stays the per-point budget ceiling.

    Returns per-point result dicts like :func:`run_grid`, plus
    ``converged``/``rounds`` fields; statistics cover exactly the runs each
    point executed."""
    known = tuple(s for s, _, _ in MOMENT_STATS)
    if ci_target_stat not in known:
        raise ValueError(
            f"unknown ci_target_stat {ci_target_stat!r}; use one of {known}"
        )
    if not (ci_target_rel and ci_target_rel > 0):
        raise ValueError("ci_target_stat needs a positive ci_target_rel")
    members = [cfg for _, cfg in points]
    names = [name for name, _ in points]
    keyset = {pack_shape_key(c) for c in members}
    if len(keyset) != 1:
        raise ValueError(
            "run_grid_adaptive needs one shape-agreement pack; plan with "
            "plan_packs"
        )
    m = members[0].network.n_miners
    n_points = len(members)
    if lanes is None:
        lanes = max(c.batch_size for c in members)
    lanes = max(lanes, n_points * min_runs)

    t0 = time.monotonic()
    eng = _make_packed_engine(
        members, engine=engine, engine_cache=engine_cache, pack_width=lanes,
    )
    eng.chaos = chaos  # run_grid parity: engine-level seams fire under drills
    width = _pad_width(lanes, eng)
    # Per-CALL params cache: adaptive rounds produce a fresh (config, count)
    # layout almost every round, so caching them in the session-lived
    # engine_cache (run_grid's static-grid win) would grow it without bound
    # — only a STABLE allocation repeating within this loop can re-hit.
    params_cache: dict = {}
    state = [
        {"sums": _zero_point_sums(m), "moments": MomentAccumulator(),
         "tele": _zero_point_tele(m), "done": 0, "converged": False,
         "rel": None}
        for _ in members
    ]

    def remaining(i: int) -> int:
        return max(0, members[i].runs - state[i]["done"])

    rounds = 0
    for rounds in range(1, max_rounds + 1):
        # Lane allocation: equal split on round 1 (no CI yet), then
        # proportional to each point's estimated remaining need.
        active = [
            i for i in range(n_points)
            if not state[i]["converged"] and remaining(i) > 0
        ]
        if not active:
            break
        need = {}
        for i in active:
            rel = state[i]["rel"]
            if rel is None:
                need[i] = 1.0
            else:
                n_i = max(state[i]["moments"].n, 1)
                need[i] = max(1.0, n_i * ((rel / ci_target_rel) ** 2 - 1.0))
        alloc = _allocate_lanes(
            active, need, {i: remaining(i) for i in active}, lanes, min_runs,
        )
        pieces = [
            _Piece(i, state[i]["done"], alloc[i])
            for i in active if alloc[i] > 0
        ]
        if not pieces:
            break
        raw = _dispatch(eng, members, pieces, width,
                        params_cache=params_cache)
        off = 0
        for p in pieces:
            _fold_piece(state[p.point], raw, slice(off, off + p.count))
            state[p.point]["done"] += p.count
            off += p.count
        for i in active:
            snap = state[i]["moments"].snapshot(target_rel_hw=ci_target_rel)
            entry = snap.get(ci_target_stat) or {}
            rel = entry.get("rel_hw_max")
            state[i]["rel"] = float(rel) if isinstance(rel, (int, float)) else None
            if state[i]["rel"] is not None and state[i]["rel"] <= ci_target_rel:
                state[i]["converged"] = True
            if telemetry is not None:
                telemetry.emit(
                    "stats", point=names[i], runs=state[i]["moments"].n,
                    runs_done=state[i]["done"], runs_total=members[i].runs,
                    duration_ms=members[i].duration_ms,
                    block_interval_s=members[i].network.block_interval_s,
                    target_rel_hw=ci_target_rel, packed=True, round=rounds,
                    lanes=alloc.get(i, 0),
                    converged=state[i]["converged"], stats=snap,
                )
        if not quiet:
            rels = ", ".join(
                f"{names[i]}={state[i]['rel'] if state[i]['rel'] is not None else '?'}"
                for i in active
            )
            print(f"[packed] round {rounds}: {rels}")
        if all(s["converged"] or remaining(i) == 0
               for i, s in enumerate(state)):
            break

    # Amortized like run_grid's: member rows sum to the true wall-clock.
    elapsed = (time.monotonic() - t0) / len(members)
    out = []
    for i, (name, cfg) in enumerate(zip(names, members)):
        st = state[i]
        res = SimResults.from_sums(
            st["sums"], cfg, mode=cfg.resolved_mode,
            elapsed_s=round(elapsed, 6),
        )
        out.append({
            "name": name, "results": res, "sums": st["sums"],
            "moments": st["moments"], "tele": st["tele"],
            "elapsed_s": elapsed, "converged": st["converged"],
            "rounds": rounds, "rel": st["rel"],
        })
    return out
