"""Vectorized xoroshiro128++ — bit-compatible with the reference generator.

The reference simulator draws everything from xoroshiro128++ seeded by two
successive splitmix64 outputs (reference xoroshiro128++.h:1-40; algorithm by
Blackman & Vigna, public domain). The TPU engine's default sampling uses JAX's
counter-based threefry instead (tpusim.sampling — statistically equivalent and
order-independent, which is what the vectorized engine needs), but a
bit-compatible generator is kept here for parity and for contract-testing the
native backend's generator from Python:

  * TPUs have no 64-bit integer ALU, so a 64-bit word lives as a uint32
    (hi, lo) pair. The xoroshiro128++ update needs only XOR, shifts and
    adds across the pair — no multiplies — so every step is a handful of
    32-bit vector ops, vectorizable over any number of independent streams.
  * Seeding (splitmix64) multiplies 64-bit constants, so it runs host-side in
    numpy uint64 (`seed_streams`), exactly as cheap and exactly once per
    stream.

``tests/test_xoroshiro.py`` pins this implementation against an independent
pure-Python big-int model and against the native backend's C++ generator
(``simcore_rng_words``), so the Python, JAX and C++ articulations of the
generator are mutually bit-exact.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = ["XoroStreams", "seed_streams", "next_words", "next_uniform", "exporand"]

U32 = jnp.uint32
_MASK32 = np.uint64(0xFFFFFFFF)


class XoroStreams(NamedTuple):
    """N independent xoroshiro128++ streams as uint32 limb arrays."""

    s0_hi: jax.Array
    s0_lo: jax.Array
    s1_hi: jax.Array
    s1_lo: jax.Array


def _splitmix64(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """One splitmix64 step: returns (advanced state, output). numpy uint64."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        z = x.copy()
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
    return x, z


def seed_streams(seeds) -> XoroStreams:
    """Seed one stream per element of ``seeds`` (uint64), reference-style:
    both state words come from successive splitmix64 outputs of the same
    advancing seed state (reference xoroshiro128++.h:9-15,23-24)."""
    s = np.atleast_1d(np.asarray(seeds, dtype=np.uint64)).copy()
    s, w0 = _splitmix64(s)
    _, w1 = _splitmix64(s)
    return XoroStreams(
        s0_hi=jnp.asarray((w0 >> np.uint64(32)).astype(np.uint32)),
        s0_lo=jnp.asarray((w0 & _MASK32).astype(np.uint32)),
        s1_hi=jnp.asarray((w1 >> np.uint64(32)).astype(np.uint32)),
        s1_lo=jnp.asarray((w1 & _MASK32).astype(np.uint32)),
    )


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(U32)
    return ah + bh + carry, lo


def _rotl64(h, l, k: int):
    k %= 64
    if k == 0:
        return h, l
    if k == 32:
        return l, h
    if k < 32:
        kk = U32(k)
        ik = U32(32 - k)
        return (h << kk) | (l >> ik), (l << kk) | (h >> ik)
    kk = U32(k - 32)
    ik = U32(64 - k)
    return (l << kk) | (h >> ik), (h << kk) | (l >> ik)


def _shl64(h, l, k: int):
    if k == 0:
        return h, l
    if k >= 32:
        return l << U32(k - 32), jnp.zeros_like(l)
    return (h << U32(k)) | (l >> U32(32 - k)), l << U32(k)


def next_words(state: XoroStreams) -> tuple[XoroStreams, jax.Array, jax.Array]:
    """Advance every stream one step; returns (state, out_hi, out_lo).

    out = rotl(s0 + s1, 17) + s0; s1 ^= s0;
    s0' = rotl(s0, 49) ^ s1 ^ (s1 << 21); s1' = rotl(s1, 28).
    """
    s0h, s0l, s1h, s1l = state
    th, tl = _add64(s0h, s0l, s1h, s1l)
    th, tl = _rotl64(th, tl, 17)
    oh, ol = _add64(th, tl, s0h, s0l)

    x1h, x1l = s1h ^ s0h, s1l ^ s0l
    r49h, r49l = _rotl64(s0h, s0l, 49)
    sh21h, sh21l = _shl64(x1h, x1l, 21)
    n0h = r49h ^ x1h ^ sh21h
    n0l = r49l ^ x1l ^ sh21l
    n1h, n1l = _rotl64(x1h, x1l, 28)
    return XoroStreams(n0h, n0l, n1h, n1l), oh, ol


def next_uniform(state: XoroStreams) -> tuple[XoroStreams, jax.Array]:
    """Uniform in [0, 1) from the top bits of the next word.

    The reference maps the top 53 bits onto a double (xoroshiro128++.h:17-20).
    On CPU (float64 enabled) this reproduces that exactly; on TPU, where only
    float32 exists, the top 24 bits are used — the generator stays bit-exact,
    only the final float mapping is quantized.
    """
    state, hi, lo = next_words(state)
    if jax.dtypes.canonicalize_dtype(jnp.float64) == jnp.float64:
        u = (hi.astype(jnp.uint64) << jnp.uint64(32) | lo.astype(jnp.uint64)) >> jnp.uint64(11)
        return state, u.astype(jnp.float64) * jnp.float64(2.0**-53)
    return state, (hi >> U32(8)).astype(jnp.float32) * jnp.float32(2.0**-24)


def exporand(state: XoroStreams, mean) -> tuple[XoroStreams, jax.Array]:
    """Exponential draw with the given mean via the reference's inverse-CDF
    construction ``-log1p(-u) * mean`` (xoroshiro128++.h:36-39)."""
    state, u = next_uniform(state)
    return state, -jnp.log1p(-u) * mean


def reference_words(seed: int, n: int) -> np.ndarray:
    """First ``n`` outputs of one stream, computed host-side in pure-Python
    big-int arithmetic — deliberately sharing no code with ``seed_streams``
    (including splitmix64), so it is a fully independent golden-value model
    for the cross-language contract tests."""
    mask = 0xFFFFFFFFFFFFFFFF

    def smix(x: int) -> tuple[int, int]:
        x = (x + 0x9E3779B97F4A7C15) & mask
        z = x
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
        return x, z ^ (z >> 31)

    def rotl(v: int, k: int) -> int:
        return ((v << k) | (v >> (64 - k))) & mask

    s, s0 = smix(int(seed) & mask)
    _, s1 = smix(s)
    out = np.empty(n, dtype=np.uint64)
    for i in range(n):
        out[i] = np.uint64((rotl((s0 + s1) & mask, 17) + s0) & mask)
        x1 = s1 ^ s0
        s0 = rotl(s0, 49) ^ x1 ^ ((x1 << 21) & mask)
        s1 = rotl(x1, 28)
    return out
