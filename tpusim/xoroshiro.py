"""Vectorized xoroshiro128++ — bit-compatible with the reference generator.

The reference simulator draws everything from xoroshiro128++ seeded by two
successive splitmix64 outputs (reference xoroshiro128++.h:1-40; algorithm by
Blackman & Vigna, public domain). The TPU engine's default sampling uses JAX's
counter-based threefry instead (tpusim.sampling — statistically equivalent and
order-independent, which is what the vectorized engine needs), but a
bit-compatible generator is kept here for parity and for contract-testing the
native backend's generator from Python:

  * TPUs have no 64-bit integer ALU, so a 64-bit word lives as a uint32
    (hi, lo) pair. The xoroshiro128++ update needs only XOR, shifts and
    adds across the pair — no multiplies — so every step is a handful of
    32-bit vector ops, vectorizable over any number of independent streams.
  * Seeding (splitmix64) multiplies 64-bit constants, so it runs host-side in
    numpy uint64 (`seed_streams`), exactly as cheap and exactly once per
    stream.

``tests/test_xoroshiro.py`` pins this implementation against an independent
pure-Python big-int model and against the native backend's C++ generator
(``simcore_rng_words``), so the Python, JAX and C++ articulations of the
generator are mutually bit-exact.
"""

from __future__ import annotations

from typing import NamedTuple

import numpy as np
import jax
import jax.numpy as jnp

__all__ = [
    "XoroStreams",
    "seed_streams",
    "next_words",
    "next_uniform",
    "uniform_from_word",
    "exporand",
    "engine_run_seeds",
    "select_streams",
    "select_stream_by_count",
    "pack_run_streams",
    "unpack_run_streams",
    "interval_ms_from_word",
    "next_words_wide",
    "winner_from_word64",
    "winners_from_words64",
    "thresholds64_limbs",
]

U32 = jnp.uint32
_MASK32 = np.uint64(0xFFFFFFFF)


class XoroStreams(NamedTuple):
    """N independent xoroshiro128++ streams as uint32 limb arrays."""

    s0_hi: jax.Array
    s0_lo: jax.Array
    s1_hi: jax.Array
    s1_lo: jax.Array


def _splitmix64(x: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """One splitmix64 step: returns (advanced state, output). numpy uint64."""
    with np.errstate(over="ignore"):
        x = x + np.uint64(0x9E3779B97F4A7C15)
        z = x.copy()
        z = (z ^ (z >> np.uint64(30))) * np.uint64(0xBF58476D1CE4E5B9)
        z = (z ^ (z >> np.uint64(27))) * np.uint64(0x94D049BB133111EB)
        z = z ^ (z >> np.uint64(31))
    return x, z


def seed_streams(seeds) -> XoroStreams:
    """Seed one stream per element of ``seeds`` (uint64), reference-style:
    both state words come from successive splitmix64 outputs of the same
    advancing seed state (reference xoroshiro128++.h:9-15,23-24)."""
    s = np.atleast_1d(np.asarray(seeds, dtype=np.uint64)).copy()
    s, w0 = _splitmix64(s)
    _, w1 = _splitmix64(s)
    return XoroStreams(
        s0_hi=jnp.asarray((w0 >> np.uint64(32)).astype(np.uint32)),
        s0_lo=jnp.asarray((w0 & _MASK32).astype(np.uint32)),
        s1_hi=jnp.asarray((w1 >> np.uint64(32)).astype(np.uint32)),
        s1_lo=jnp.asarray((w1 & _MASK32).astype(np.uint32)),
    )


def _add64(ah, al, bh, bl):
    lo = al + bl
    carry = (lo < al).astype(U32)
    return ah + bh + carry, lo


def _rotl64(h, l, k: int):
    k %= 64
    if k == 0:
        return h, l
    if k == 32:
        return l, h
    if k < 32:
        kk = U32(k)
        ik = U32(32 - k)
        return (h << kk) | (l >> ik), (l << kk) | (h >> ik)
    kk = U32(k - 32)
    ik = U32(64 - k)
    return (l << kk) | (h >> ik), (h << kk) | (l >> ik)


def _shl64(h, l, k: int):
    if k == 0:
        return h, l
    if k >= 32:
        return l << U32(k - 32), jnp.zeros_like(l)
    return (h << U32(k)) | (l >> U32(32 - k)), l << U32(k)


def next_words(state: XoroStreams) -> tuple[XoroStreams, jax.Array, jax.Array]:
    """Advance every stream one step; returns (state, out_hi, out_lo).

    out = rotl(s0 + s1, 17) + s0; s1 ^= s0;
    s0' = rotl(s0, 49) ^ s1 ^ (s1 << 21); s1' = rotl(s1, 28).
    """
    s0h, s0l, s1h, s1l = state
    th, tl = _add64(s0h, s0l, s1h, s1l)
    th, tl = _rotl64(th, tl, 17)
    oh, ol = _add64(th, tl, s0h, s0l)

    x1h, x1l = s1h ^ s0h, s1l ^ s0l
    r49h, r49l = _rotl64(s0h, s0l, 49)
    sh21h, sh21l = _shl64(x1h, x1l, 21)
    n0h = r49h ^ x1h ^ sh21h
    n0l = r49l ^ x1l ^ sh21l
    n1h, n1l = _rotl64(x1h, x1l, 28)
    return XoroStreams(n0h, n0l, n1h, n1l), oh, ol


def next_words_wide(
    state: XoroStreams, k: int
) -> tuple[list[XoroStreams], jax.Array, jax.Array]:
    """Draw the next ``k`` outputs of every stream in one wide pass: returns
    (the k successively-advanced states, out_hi (k, ...), out_lo (k, ...)).

    Output word ``c`` is exactly the word ``c + 1`` sequential
    :func:`next_words` calls would produce (pinned by
    tests/test_rng_batch.py), so a consumer that takes word ``c`` for its
    ``c``-th consumed draw and ends on ``states[c_total - 1]`` replays the
    reference's conditional-advance stream order bit-for-bit — the
    batched-RNG discipline of SimConfig.rng_batch: the sampler is
    vectorized, the consumption order is not changed.
    """
    states: list[XoroStreams] = []
    his, los = [], []
    for _ in range(k):
        state, h, l = next_words(state)
        states.append(state)
        his.append(h)
        los.append(l)
    return states, jnp.stack(his), jnp.stack(los)


def select_stream_by_count(
    count: jax.Array, state0: XoroStreams, states: list[XoroStreams]
) -> XoroStreams:
    """The stream state after ``count`` consumed draws, selected from a
    :func:`next_words_wide` lookahead: ``count == 0`` keeps ``state0``,
    ``count == c`` takes ``states[c - 1]`` — the wide path's equivalent of
    per-event :func:`select_streams`."""
    def pick(i: int):
        stacked = jnp.stack(
            [state0[i]] + [s[i] for s in states]
        )  # (k + 1, ...)
        onehot = jnp.arange(len(states) + 1) == count
        shape = (-1,) + (1,) * (stacked.ndim - 1)
        return jnp.sum(
            jnp.where(onehot.reshape(shape), stacked, U32(0)), axis=0, dtype=U32
        )

    return XoroStreams(*(pick(i) for i in range(4)))


def uniform_from_word(hi: jax.Array, lo: jax.Array) -> jax.Array:
    """Uniform in [0, 1) from one 64-bit generator word's uint32 limbs.

    The reference maps the top 53 bits onto a double (xoroshiro128++.h:17-20).
    With float64 available this reproduces that exactly; on TPU, where only
    float32 exists, the top 24 bits are used — the generator stays bit-exact,
    only the final float mapping is quantized. (The int32 detour on the
    float32 path exists because Mosaic has no uint32->float32 cast; after
    >>8 the value fits in 24 bits, so it is exact.)
    """
    if jax.dtypes.canonicalize_dtype(jnp.float64) == jnp.float64:
        u = (hi.astype(jnp.uint64) << jnp.uint64(32) | lo.astype(jnp.uint64)) >> jnp.uint64(11)
        return u.astype(jnp.float64) * jnp.float64(2.0**-53)
    return (hi >> U32(8)).astype(jnp.int32).astype(jnp.float32) * jnp.float32(2.0**-24)


def next_uniform(state: XoroStreams) -> tuple[XoroStreams, jax.Array]:
    """Advance every stream one step and map the word to uniform [0, 1)."""
    state, hi, lo = next_words(state)
    return state, uniform_from_word(hi, lo)


def exporand(state: XoroStreams, mean) -> tuple[XoroStreams, jax.Array]:
    """Exponential draw with the given mean via the reference's inverse-CDF
    construction ``-log1p(-u) * mean`` (xoroshiro128++.h:36-39)."""
    state, u = next_uniform(state)
    return state, -jnp.log1p(-u) * mean


# --- engine integration (rng="xoroshiro") ----------------------------------
# The engine replaces its counter-based threefry draws with two sequential
# per-run streams matching the native backend's derivation
# (native/simcore.cpp simulate_run): mix = splitmix64-advance(seed), then
# interval_seed = mix ^ (C * (2*run+1)), winner_seed = mix ^ (C * (2*run+2)).

_GOLDEN = np.uint64(0x9E3779B97F4A7C15)
_STREAM_C = np.uint64(0x517CC1B727220A95)


def engine_run_seeds(seed: int, start: int, count: int) -> tuple[np.ndarray, np.ndarray]:
    """(interval_seeds, winner_seeds) for global run indices [start, start+count),
    bit-matching the native backend's per-run stream derivation."""
    with np.errstate(over="ignore"):
        # Mask to the C++ uint64 conversion semantics so negative seeds (fine
        # for the threefry path) work identically here.
        mix = np.uint64(seed & 0xFFFFFFFFFFFFFFFF) + _GOLDEN  # (void)splitmix64(mix)
        idx = np.arange(start, start + count, dtype=np.uint64)
        return (
            mix ^ (_STREAM_C * (np.uint64(2) * idx + np.uint64(1))),
            mix ^ (_STREAM_C * (np.uint64(2) * idx + np.uint64(2))),
        )


def pack_run_streams(seed: int, start: int, count: int) -> np.ndarray:
    """Seed both per-run streams and pack them as one (count, 8) uint32 array
    — the engine's opaque per-run sampling-identity input ("keys") for
    rng="xoroshiro". Layout: interval stream limbs [0:4], winner [4:8], each
    as (s0_hi, s0_lo, s1_hi, s1_lo)."""
    si, sw = engine_run_seeds(seed, start, count)
    a, b = seed_streams(si), seed_streams(sw)
    return np.stack(
        [np.asarray(x, dtype=np.uint32) for x in (*a, *b)], axis=1
    )


def unpack_run_streams(packed: jax.Array) -> tuple[XoroStreams, XoroStreams]:
    """Inverse of :func:`pack_run_streams` for one run (vmapped by the engine):
    takes the (8,) uint32 row, returns (interval_stream, winner_stream)."""
    return (
        XoroStreams(packed[0], packed[1], packed[2], packed[3]),
        XoroStreams(packed[4], packed[5], packed[6], packed[7]),
    )


def select_streams(pred: jax.Array, new: XoroStreams, old: XoroStreams) -> XoroStreams:
    """Per-stream conditional advance: the sequential generator only moves
    when its draw was actually consumed (unlike threefry, which burns one
    counter per scan step unconditionally)."""
    return XoroStreams(*(jnp.where(pred, n, o) for n, o in zip(new, old)))


def thresholds64_limbs(thresholds_u64: np.ndarray) -> tuple[np.ndarray, np.ndarray]:
    """Split the reference's cumulative uint64 winner thresholds
    (sampling.winner_thresholds) into uint32 (hi, lo) limb arrays for the
    TPU-native 64-bit comparison in :func:`winner_from_word64`."""
    return (
        (thresholds_u64 >> np.uint64(32)).astype(np.uint32),
        (thresholds_u64 & _MASK32).astype(np.uint32),
    )


def winner_from_word64(hi: jax.Array, lo: jax.Array, thr_hi: jax.Array,
                       thr_lo: jax.Array) -> jax.Array:
    """First miner whose cumulative uint64 threshold strictly exceeds the
    64-bit draw (native simcore draw_winner; reference simulation.h:213-221),
    clamped to the last miner for the ~16/2^64 overflow draws — as pure
    uint32 limb compares, bit-exact on TPU."""
    le = (thr_hi < hi) | ((thr_hi == hi) & (thr_lo <= lo))  # threshold <= draw
    w = jnp.sum(le, dtype=jnp.int32)
    return jnp.minimum(w, jnp.int32(thr_hi.shape[0] - 1))


def winners_from_words64(hi: jax.Array, lo: jax.Array, thr_hi: jax.Array,
                         thr_lo: jax.Array) -> jax.Array:
    """Vectorized :func:`winner_from_word64` over any leading shape of draws
    (the wide lookahead of :func:`next_words_wide`): same limb compares, same
    sum, same clamp per element, so consuming these precomputed winners is
    bit-equal to mapping each word at its event."""
    h, l = hi[..., None], lo[..., None]
    le = (thr_hi < h) | ((thr_hi == h) & (thr_lo <= l))
    w = jnp.sum(le, axis=-1, dtype=jnp.int32)
    return jnp.minimum(w, jnp.int32(thr_hi.shape[0] - 1))


def interval_ms_from_word(hi: jax.Array, lo: jax.Array, mean_interval_ms,
                          cap_ms: float) -> jax.Array:
    """Block interval in integer ms (int32) from one 64-bit generator word,
    following the native/reference construction: uniform from the top bits
    (:func:`uniform_from_word`), exponential in NANOseconds, llround,
    truncate to ms (native simcore draw_interval; reference
    simulation.h:205-210).

    With float64 available (CPU tests run the A/B harness under
    JAX_ENABLE_X64) this is bit-exact vs the native backend. On TPU there is
    no float64: the 24-bit float32 uniform perturbs a draw by ~6e-8 relative
    — the generator words themselves stay bit-exact.
    """
    u = uniform_from_word(hi, lo)
    if u.dtype == jnp.float64:
        expo_ns = -jnp.log1p(-u) * jnp.float64(mean_interval_ms * 1e6)
        ns = jnp.floor(expo_ns + 0.5)  # llround for positive values
        ms = jnp.floor(ns / 1e6)
        return jnp.minimum(ms, cap_ms).astype(jnp.int32)
    expo_ms = -jnp.log1p(-u) * jnp.float32(mean_interval_ms)
    return jnp.minimum(expo_ms, jnp.float32(cap_ms)).astype(jnp.int32)


def reference_words(seed: int, n: int) -> np.ndarray:
    """First ``n`` outputs of one stream, computed host-side in pure-Python
    big-int arithmetic — deliberately sharing no code with ``seed_streams``
    (including splitmix64), so it is a fully independent golden-value model
    for the cross-language contract tests."""
    mask = 0xFFFFFFFFFFFFFFFF

    def smix(x: int) -> tuple[int, int]:
        x = (x + 0x9E3779B97F4A7C15) & mask
        z = x
        z = ((z ^ (z >> 30)) * 0xBF58476D1CE4E5B9) & mask
        z = ((z ^ (z >> 27)) * 0x94D049BB133111EB) & mask
        return x, z ^ (z >> 31)

    def rotl(v: int, k: int) -> int:
        return ((v << k) | (v >> (64 - k))) & mask

    s, s0 = smix(int(seed) & mask)
    _, s1 = smix(s)
    out = np.empty(n, dtype=np.uint64)
    for i in range(n):
        out[i] = np.uint64((rotl((s0 + s1) & mask, 17) + s0) & mask)
        x1 = s1 ^ s0
        s0 = rotl(s0, 49) ^ x1 ^ ((x1 << 21) & mask)
        s1 = rotl(x1, 28)
    return out
