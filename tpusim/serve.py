"""``tpusim serve`` — the crash-only simulation service.

A long-lived daemon that answers simulation queries over HTTP. The front
half is jax-free: a stdlib ``ThreadingHTTPServer`` (the
``metrics.serve_metrics`` discipline — tolerant handlers, no framework)
doing admission control against a **bounded** request queue. The back half
is a single engine-owning dispatch worker thread that drains the queue,
groups heterogeneous queries by ``packed.pack_shape_key`` and dispatches
each group as ONE packed ``run_grid`` batch against the session-lived
engine cache (``Engine.reuse_key``) — so a warmed mixed-shape storm
compiles nothing and queries coalesced into a shared pack each pay the
pack-amortized latency, not the sum.

Crash-only design, enforced seam by seam:

* **Admission rejects loud.** A full queue (or a draining daemon) returns
  a retryable 503 carrying the current depth and an ETA estimate — never
  silent buffering. ``serve.accept`` is the chaos seam.
* **Deadlines shed, the daemon lives.** Every query carries a wall-clock
  deadline; dispatches run under :func:`tpusim.chaos.fetch_with_deadline`
  (the fleet's wall-clock-watchdog discipline), so ONE wedged dispatch
  sheds exactly the queries riding that pack — concurrent packs keep
  answering. ``serve.dispatch`` is the seam; an
  :class:`~tpusim.chaos.InjectedHang` there is treated exactly as a
  watchdog expiry.
* **Results are cached and provenance-chained.** Answers are cached by
  (config sampling fingerprint, seed, runs, budget); a hit serves the
  cached row BIT-EQUAL and its lineage record cites the original answer
  as parent (``served_query`` kind). Served rows append to
  ``<state-dir>/rows.jsonl`` in the exact ``run_sweep`` row shape, so
  ``tpusim audit`` resolves every served answer. ``serve.cache`` is the
  seam: ENOSPC on the row write disables persistence and the daemon keeps
  serving from memory.
* **SIGTERM drains gracefully.** Stop accepting (503), finish or
  explicitly shed every accepted query, flush the result rows, the
  telemetry ledger and the lineage ledger, write a ``drain.json``
  accounting summary, exit 0. ``serve.drain`` is the seam.

Budgets: a query may pass ``ci_target_stat``/``ci_target_rel`` instead of
trusting its fixed ``runs`` — the group then dispatches through
``run_grid_adaptive`` ("answer to 1% CI or deadline, whichever first":
convergence stops early, the watchdog deadline sheds late).

Every query streams progress: the daemon's recorder adopts
``TPUSIM_TRACE_CONTEXT`` like any fleet worker, so ``serve_accept`` /
``serve_progress`` / ``serve_query`` spans land in the caller's trace and
``tpusim metrics``/``slo`` derive the service SLOs (profile ``serve``)
from the same state dir.
"""

from __future__ import annotations

import argparse
import json
import logging
import queue
import signal
import sys
import threading
import time
from pathlib import Path
from typing import Any

from .chaos import ChaosError, ChaosPermanentError, as_injector
from .config import SimConfig
from .provenance import emit_lineage, lineage_armed

logger = logging.getLogger("tpusim.serve")

__all__ = ["ServeDaemon", "ServeReject", "main"]

#: Default bounded request-queue depth (admission control rejects beyond it).
DEFAULT_QUEUE_DEPTH = 64

#: Default per-query wall-clock deadline (seconds).
DEFAULT_DEADLINE_S = 120.0

#: Fallback per-dispatch seconds used for queue-ETA estimates before the
#: first dispatch has been measured.
_ETA_SEED_S = 2.0

#: Extra handler-side wait beyond a query's deadline before the handler
#: gives up on the worker (the worker always resolves queries; this cap
#: only bounds the HTTP thread if the daemon is torn down mid-request).
_HANDLER_GRACE_S = 30.0


class ServeReject(RuntimeError):
    """An admission rejection: loud, structured, usually retryable."""

    def __init__(
        self, reason: str, *, retryable: bool = True,
        depth: int = 0, eta_s: float | None = None,
    ):
        super().__init__(reason)
        self.reason = reason
        self.retryable = retryable
        self.depth = depth
        self.eta_s = eta_s


class _Query:
    """One accepted query riding the queue. Cross-thread handoff happens
    through ``done`` (a per-query Event): the worker writes the result
    fields then sets it; the HTTP handler waits on it (timed) and reads.
    """

    __slots__ = (
        "name", "config", "ci_target_stat", "ci_target_rel", "deadline_s",
        "t0_wall", "t0_mono", "deadline_mono", "done", "row", "moments",
        "extra", "address", "status", "reason", "cache_hit",
        "depth_at_accept", "cache_key", "group_key",
    )

    def __init__(
        self, name: str, config: SimConfig, *,
        ci_target_stat: str | None, ci_target_rel: float | None,
        deadline_s: float,
    ):
        self.name = name
        self.config = config
        self.ci_target_stat = ci_target_stat
        self.ci_target_rel = ci_target_rel
        self.deadline_s = float(deadline_s)
        self.t0_wall = time.time()
        self.t0_mono = time.monotonic()
        self.deadline_mono = self.t0_mono + self.deadline_s
        self.done = threading.Event()
        self.row: dict[str, Any] | None = None
        self.moments: dict[str, Any] | None = None
        self.extra: dict[str, Any] = {}
        self.address: str | None = None
        self.status: str | None = None
        self.reason: str | None = None
        self.cache_hit = False
        self.depth_at_accept = 0
        self.cache_key: tuple | None = None
        self.group_key: tuple | None = None


def _moments_payload(acc) -> dict[str, Any] | None:
    """A MomentAccumulator's exact int64 state as JSON-exact Python ints —
    the bit-equality surface clients (and tests) compare against a direct
    ``run_grid`` of the same configs."""
    if acc is None:
        return None
    return {
        "n": int(acc.n),
        "m1": {k: [int(x) for x in v] for k, v in acc.m1.items()},
        "m2": {k: [int(x) for x in v] for k, v in acc.m2.items()},
    }


class ServeDaemon:
    """The daemon: bounded queue in front, one dispatch worker behind.

    Threads (both non-daemon, both joined by :meth:`drain`): the HTTP
    accept loop and the dispatch worker. All daemon-shared mutable state
    (counters, ETA estimate, persistence flag) is guarded by ``_lock`` on
    BOTH sides — the JX015 contract the lint gate enforces. Per-query state
    is handed off through each query's own Event instead of shared
    attributes, so the queue is the only cross-thread channel.
    """

    def __init__(
        self,
        state_dir: str | Path,
        *,
        host: str = "127.0.0.1",
        port: int = 0,
        queue_depth: int = DEFAULT_QUEUE_DEPTH,
        deadline_s: float = DEFAULT_DEADLINE_S,
        engine: str = "auto",
        chaos=None,
    ):
        self.state_dir = Path(state_dir)
        self.host = host
        self.port = port
        self.default_deadline_s = float(deadline_s)
        self.engine = engine
        self._chaos = as_injector(chaos)
        self._lock = threading.Lock()
        self._queue: queue.Queue[_Query] = queue.Queue(maxsize=queue_depth)
        self._stop = threading.Event()
        self._draining = False
        self._counters = {
            "accepted": 0, "served": 0, "shed": 0, "rejected": 0,
            "cache_hits": 0, "coalesced": 0, "compiles": 0,
            "cache_write_failures": 0,
        }
        self._accepted: list[_Query] = []
        self._avg_dispatch_s: float | None = None
        self._rows_disabled = False
        self._results: dict[tuple, dict[str, Any]] = {}  # worker-owned
        self._engine_cache: dict = {}  # worker-owned
        self._recorder = None
        self._server = None
        self._http_thread: threading.Thread | None = None
        self._worker: threading.Thread | None = None
        self._rows_path = self.state_dir / "rows.jsonl"

    # -- lifecycle ---------------------------------------------------------

    def start(self) -> None:
        self.start_http()
        self.start_worker()

    def _ensure_recorder(self) -> None:
        if self._recorder is None:
            from .telemetry import TelemetryRecorder

            self.state_dir.mkdir(parents=True, exist_ok=True)
            self._recorder = TelemetryRecorder(
                self.state_dir / "serve.tele.jsonl", chaos=self._chaos
            )
            if self._chaos is not None:
                self._chaos.bind_telemetry(self._recorder)

    def start_http(self) -> None:
        """Bind the listener and start the accept loop. Split from
        :meth:`start_worker` so tests can admit queries against a full
        queue before any dispatch drains it."""
        self._ensure_recorder()
        self._server = self._build_server()
        host, port = self._server.server_address[:2]
        try:
            (self.state_dir / "endpoint.json").write_text(
                json.dumps({"url": f"http://{host}:{port}",
                            "host": str(host), "port": int(port)})
            )
        except OSError as e:
            logger.warning("could not write endpoint.json: %s", e)
        self._http_thread = threading.Thread(
            target=self._http_loop, name="tpusim-serve-http"
        )
        self._http_thread.start()

    def _http_loop(self) -> None:
        self._server.serve_forever(poll_interval=0.2)

    def start_worker(self) -> None:
        self._ensure_recorder()
        self._worker = threading.Thread(
            target=self._dispatch_loop, name="tpusim-serve-dispatch"
        )
        self._worker.start()

    @property
    def url(self) -> str:
        host, port = self._server.server_address[:2]
        return f"http://{host}:{port}"

    def stop(self) -> None:
        """Request drain (what the SIGTERM handler triggers via its Event in
        :func:`main`; in-process callers may call :meth:`drain` directly)."""
        self._stop.set()

    def drain(self) -> dict[str, Any]:
        """Graceful drain: stop accepting, finish (or explicitly shed)
        every accepted query, flush every ledger, return the accounting
        summary (also written to ``<state-dir>/drain.json``)."""
        if self._chaos is not None:
            try:
                self._chaos.fire("serve.drain", depth=self._queue.qsize())
            except (ChaosError, ChaosPermanentError, OSError) as e:
                # A fault at the drain seam must not stop the drain: the
                # whole point of crash-only shutdown is that it completes.
                logger.warning("chaos at serve.drain: %s (draining anyway)", e)
        with self._lock:
            self._draining = True
        self._stop.set()
        if self._server is not None:
            self._server.shutdown()
        if self._http_thread is not None:
            self._http_thread.join()
            self._http_thread = None
        if self._worker is not None:
            self._worker.join()
            self._worker = None
        with self._lock:
            accepted = list(self._accepted)
        # Belt and braces: the worker resolves everything it dequeued and
        # drains the queue before exiting, so this loop should find nothing
        # — but an accepted query must NEVER be silently lost.
        for q in accepted:
            if not q.done.is_set():
                self._resolve_shed(q, "shed at shutdown (drain)")
        with self._lock:
            counters = dict(self._counters)
        summary = {
            **counters,
            "clean": counters["accepted"]
            == counters["served"] + counters["shed"],
        }
        self._emit(
            "serve_drain",
            accepted=counters["accepted"], served=counters["served"],
            shed=counters["shed"], rejected=counters["rejected"],
        )
        try:
            (self.state_dir / "drain.json").write_text(json.dumps(summary))
        except OSError as e:
            logger.warning("could not write drain.json: %s", e)
        if self._server is not None:
            self._server.server_close()
            self._server = None
        if self._recorder is not None:
            self._recorder.close()
        return summary

    # -- front half (jax-free) ---------------------------------------------

    def _emit(self, span: str, **attrs: Any) -> None:
        rec = self._recorder
        if rec is not None:
            rec.emit(span, **attrs)

    def submit(
        self,
        name: str,
        config: SimConfig,
        *,
        ci_target_stat: str | None = None,
        ci_target_rel: float | None = None,
        deadline_s: float | None = None,
    ) -> _Query:
        """Admission control: enqueue one query or raise
        :class:`ServeReject` — loud, with depth and ETA, never silent."""
        if self._chaos is not None:
            try:
                self._chaos.fire("serve.accept", target=name)
            except ChaosError as e:
                self._note_reject(f"transient admission fault: {e}")
            except ChaosPermanentError as e:
                self._note_reject(f"permanent admission fault: {e}",
                                  retryable=False)
            except OSError as e:
                self._note_reject(f"admission I/O fault: {e}")
        if ci_target_stat is not None and ci_target_rel is None:
            ci_target_rel = 0.01
        q = _Query(
            name, config,
            ci_target_stat=ci_target_stat, ci_target_rel=ci_target_rel,
            deadline_s=self.default_deadline_s if deadline_s is None
            else float(deadline_s),
        )
        reject: tuple[str, int, float | None] | None = None
        depth = 0
        with self._lock:
            avg = self._avg_dispatch_s or _ETA_SEED_S
            if self._draining:
                reject = ("draining: not accepting new queries",
                          self._queue.qsize(), None)
            else:
                try:
                    self._queue.put_nowait(q)
                except queue.Full:
                    d = self._queue.qsize()
                    reject = ("queue full", d, round(avg * (d + 1), 3))
                else:
                    self._counters["accepted"] += 1
                    self._accepted.append(q)
                    depth = self._queue.qsize()
        if reject is not None:
            self._note_reject(reject[0], depth=reject[1], eta_s=reject[2])
        q.depth_at_accept = depth
        self._emit("serve_accept", point=name, depth=depth)
        return q

    def _note_reject(
        self, reason: str, *, retryable: bool = True, depth: int = 0,
        eta_s: float | None = None,
    ) -> None:
        with self._lock:
            self._counters["rejected"] += 1
        self._emit("serve_reject", reason=reason, depth=depth)
        raise ServeReject(reason, retryable=retryable, depth=depth, eta_s=eta_s)

    def stats_snapshot(self) -> dict[str, Any]:
        with self._lock:
            counters = dict(self._counters)
            draining = self._draining
            avg = self._avg_dispatch_s
            rows_disabled = self._rows_disabled
        return {
            "counters": counters,
            "accepting": not draining,
            "queue_depth": self._queue.qsize(),
            "avg_dispatch_s": avg,
            "results_cached": len(self._results),
            "rows_persisted": not rows_disabled,
        }

    def _build_server(self):
        from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

        daemon = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, code: int, payload: dict) -> None:
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                try:
                    snap = daemon.stats_snapshot()
                    if path == "/healthz":
                        self._send(200, {
                            "ok": True,
                            "accepting": snap["accepting"],
                            "queue_depth": snap["queue_depth"],
                            "state_dir": str(daemon.state_dir),
                        })
                    elif path == "/api/stats":
                        self._send(200, snap)
                    else:
                        self._send(404, {"error": "not found"})
                except BrokenPipeError:  # client hung up mid-response
                    pass
                except Exception as e:  # noqa: BLE001 - a probe must never kill the server
                    try:
                        self._send(500, {"error": str(e)})
                    except OSError:
                        pass

            def do_POST(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
                path = self.path.split("?", 1)[0]
                try:
                    if path != "/api/query":
                        self._send(404, {"error": "not found"})
                        return
                    try:
                        length = int(self.headers.get("Content-Length") or 0)
                        body = json.loads(self.rfile.read(length) or b"{}")
                        q = daemon._admit(body)
                    except ServeReject as e:
                        self._send(503, {
                            "status": "rejected", "error": e.reason,
                            "retryable": e.retryable,
                            "queue_depth": e.depth, "eta_s": e.eta_s,
                        })
                        return
                    except (KeyError, TypeError, ValueError) as e:
                        self._send(400, {"status": "invalid",
                                         "error": str(e), "retryable": False})
                        return
                    self._send(*daemon._await_query(q))
                except BrokenPipeError:  # client hung up mid-response
                    pass
                except Exception as e:  # noqa: BLE001 - a query must never kill the server
                    try:
                        self._send(500, {"error": str(e)})
                    except OSError:
                        pass

            def log_message(self, *args) -> None:  # quiet by default
                pass

        return ThreadingHTTPServer((self.host, self.port), Handler)

    def _admit(self, body: dict[str, Any]) -> _Query:
        """Parse one ``POST /api/query`` body and submit it. Raises
        ValueError/KeyError (→ 400) on shape problems, ServeReject (→ 503)
        on admission control."""
        if not isinstance(body, dict):
            raise ValueError("request body must be a JSON object")
        cfg_dict = body.get("config")
        if not isinstance(cfg_dict, dict):
            raise ValueError('request needs a "config" object (SimConfig JSON)')
        cfg_dict = dict(cfg_dict)
        for field in ("runs", "seed"):
            if field in body:
                cfg_dict[field] = body[field]
        config = SimConfig.from_json(json.dumps(cfg_dict))
        if config.runs < 1:
            raise ValueError("config.runs must be >= 1")
        name = str(body.get("name") or f"q-{config.seed}-{config.runs}")
        stat = body.get("ci_target_stat")
        if stat is not None and not isinstance(stat, str):
            raise ValueError("ci_target_stat must be a string statistic name")
        rel = body.get("ci_target_rel")
        if rel is not None:
            rel = float(rel)
            if rel <= 0:
                raise ValueError("ci_target_rel must be > 0")
        deadline = body.get("deadline_s")
        if deadline is not None:
            deadline = float(deadline)
            if deadline <= 0:
                raise ValueError("deadline_s must be > 0")
        return self.submit(
            name, config, ci_target_stat=stat, ci_target_rel=rel,
            deadline_s=deadline,
        )

    def _await_query(self, q: _Query) -> tuple[int, dict[str, Any]]:
        """Block the handler thread (timed waits only) until the worker
        resolves ``q``, then render the response."""
        cap = q.deadline_mono + _HANDLER_GRACE_S
        while not q.done.is_set() and time.monotonic() < cap:
            q.done.wait(timeout=0.25)
        if not q.done.is_set():
            return 500, {"status": "lost", "error":
                         "query unresolved past deadline + grace", "point": q.name}
        if q.status == "served":
            payload: dict[str, Any] = {
                "status": "served",
                "point": q.name,
                "cache_hit": q.cache_hit,
                "row": q.row,
                "moments": q.moments,
                "address": q.address,
                "queue_depth_at_accept": q.depth_at_accept,
            }
            payload.update(q.extra)
            return 200, payload
        return 504, {
            "status": "shed", "error": q.reason or "shed",
            "retryable": True, "point": q.name,
        }

    # -- back half (the one engine-owning worker thread) -------------------

    def _dispatch_loop(self) -> None:
        from .testing import subscribe_backend_compiles

        def _on_compile(_name: str, _secs: float) -> None:
            with self._lock:
                self._counters["compiles"] += 1

        unsubscribe = subscribe_backend_compiles(_on_compile)
        try:
            while True:
                try:
                    first = self._queue.get(timeout=0.2)
                except queue.Empty:
                    if self._stop.is_set():
                        return
                    continue
                batch = [first]
                while len(batch) < 256:
                    try:
                        batch.append(self._queue.get_nowait())
                    except queue.Empty:
                        break
                try:
                    self._process(batch)
                except Exception:  # noqa: BLE001 - crash-only: one batch must never kill the daemon
                    logger.exception("serve dispatch batch failed")
                    for q in batch:
                        if not q.done.is_set():
                            self._resolve_shed(q, "internal dispatch error")
        finally:
            unsubscribe()

    def _process(self, batch: list[_Query]) -> None:
        from .packed import pack_shape_key
        from .runner import checkpoint_fingerprint

        now = time.monotonic()
        live: list[_Query] = []
        for q in batch:
            if now >= q.deadline_mono:
                self._resolve_shed(q, "deadline exceeded while queued")
                continue
            cfg = q.config
            q.cache_key = (
                checkpoint_fingerprint(cfg, cfg.resolved_chunk_steps),
                cfg.seed, cfg.runs, q.ci_target_stat, q.ci_target_rel,
            )
            q.group_key = (
                pack_shape_key(cfg), q.ci_target_stat, q.ci_target_rel,
            )
            live.append(q)
        misses: list[_Query] = []
        for q in live:
            ent = self._results.get(q.cache_key)
            if ent is not None:
                self._resolve_served(q, ent, cache_hit=True)
            else:
                misses.append(q)
        groups: dict[tuple, list[_Query]] = {}
        for q in misses:
            groups.setdefault(q.group_key, []).append(q)
        for qs in groups.values():
            self._dispatch_group(qs)

    def _dispatch_group(self, qs: list[_Query]) -> None:
        """One packed dispatch for one shape-agreement group, under the
        wall-clock watchdog. Identical queries within the group coalesce
        onto one computed point."""
        from .chaos import InjectedHang, PipelineStallError, fetch_with_deadline

        uniq: dict[tuple, list[_Query]] = {}
        for q in qs:
            uniq.setdefault(q.cache_key, []).append(q)
        leaders = [group[0] for group in uniq.values()]
        names: list[str] = []
        seen: set[str] = set()
        for q in leaders:
            nm = q.name
            while nm in seen:
                nm += "~"
            seen.add(nm)
            names.append(nm)
        points = [(nm, q.config) for nm, q in zip(names, leaders)]
        adaptive = leaders[0].ci_target_stat is not None
        t_disp = time.monotonic()
        timeout = max(0.5, min(q.deadline_mono for q in qs) - t_disp)

        def thunk():
            if self._chaos is not None:
                self._chaos.fire(
                    "serve.dispatch", points=len(points), queries=len(qs),
                    adaptive=adaptive,
                )
            from .packed import run_grid, run_grid_adaptive

            if adaptive:
                return run_grid_adaptive(
                    points,
                    ci_target_stat=leaders[0].ci_target_stat,
                    ci_target_rel=leaders[0].ci_target_rel or 0.01,
                    engine=self.engine, engine_cache=self._engine_cache,
                    telemetry=self._recorder, chaos=self._chaos,
                )

            def progress(done_runs: int, total_runs: int) -> None:
                self._emit(
                    "serve_progress", done_runs=int(done_runs),
                    total_runs=int(total_runs), queries=len(qs),
                )

            return run_grid(
                points, engine=self.engine, engine_cache=self._engine_cache,
                telemetry=self._recorder, chaos=self._chaos,
                progress=progress,
            )

        try:
            out = fetch_with_deadline(thunk, timeout, what="packed serve dispatch")
        except (InjectedHang, PipelineStallError) as e:
            # The watchdog expired (or the hang drill simulated exactly
            # that): shed ONLY this pack's queries; the daemon stays live.
            for q in qs:
                self._resolve_shed(q, f"wedged dispatch: {e}")
            return
        except Exception as e:  # noqa: BLE001 - crash-only: shed the pack, keep serving
            for q in qs:
                self._resolve_shed(
                    q, f"dispatch failed: {type(e).__name__}: {e}"
                )
            return
        elapsed = time.monotonic() - t_disp
        with self._lock:
            prev = self._avg_dispatch_s
            self._avg_dispatch_s = (
                elapsed if prev is None else round(0.5 * prev + 0.5 * elapsed, 6)
            )
        for entry, (key, group) in zip(out, uniq.items()):
            # EXACTLY the run_sweep packed row shape: served answers must be
            # bit-equal to a direct sweep of the same configs.
            row = {
                **entry["results"].to_dict(),
                "point": entry["name"],
                "backend": "tpu",
                "elapsed_s": round(entry["elapsed_s"], 3),
            }
            extra = {
                k: entry[k] for k in ("converged", "rounds", "rel")
                if k in entry
            }
            address = None
            if lineage_armed():
                address = emit_lineage(
                    "served_query", content=row, point=row.get("point"),
                    runs=row.get("runs"), backend="tpu", cache_hit=False,
                )
            self._persist_row(row)
            ent = {
                "row": row, "moments": _moments_payload(entry.get("moments")),
                "address": address, "extra": extra,
            }
            self._results[key] = ent
            for i, q in enumerate(group):
                self._resolve_served(
                    q, ent, cache_hit=i > 0, coalesced=len(group) > 1
                )

    def _persist_row(self, row: dict[str, Any]) -> None:
        """Append one served row to the durable result cache. ENOSPC (real
        or drilled via ``serve.cache``) disables persistence — warn once,
        keep serving from memory; the gap fails loud in ``tpusim audit``."""
        with self._lock:
            disabled = self._rows_disabled
        if disabled:
            return
        try:
            if self._chaos is not None:
                self._chaos.fire("serve.cache", target=row.get("point"))
            from .telemetry import append_jsonl_line

            append_jsonl_line(self._rows_path, json.dumps(row))
        except OSError as e:
            with self._lock:
                self._rows_disabled = True
                self._counters["cache_write_failures"] += 1
            logger.warning(
                "disabling served-row persistence after write failure "
                "(%s: %s); the daemon keeps serving from memory",
                type(e).__name__, e,
            )

    def _resolve_served(
        self, q: _Query, ent: dict[str, Any], *, cache_hit: bool,
        coalesced: bool = False,
    ) -> None:
        address = ent["address"]
        if cache_hit and lineage_armed():
            # The hit's own lineage record: same content (bit-equal row),
            # parent = the answer it was served from.
            row = ent["row"]
            address = emit_lineage(
                "served_query", content=row, parents=[ent["address"]],
                point=row.get("point"), runs=row.get("runs"),
                backend="tpu", cache_hit=True,
            ) or address
        q.row = ent["row"]
        q.moments = ent["moments"]
        q.extra = dict(ent["extra"])
        q.address = address
        q.cache_hit = cache_hit
        q.status = "served"
        with self._lock:
            self._counters["served"] += 1
            if cache_hit:
                self._counters["cache_hits"] += 1
            if coalesced:
                self._counters["coalesced"] += 1
        self._emit(
            "serve_query",
            t_start=q.t0_wall, dur_s=time.monotonic() - q.t0_mono,
            point=q.name, status="served", cache_hit=cache_hit,
            runs=(q.row or {}).get("runs"),
        )
        q.done.set()

    def _resolve_shed(self, q: _Query, reason: str) -> None:
        q.status = "shed"
        q.reason = reason
        with self._lock:
            self._counters["shed"] += 1
        self._emit(
            "serve_query",
            t_start=q.t0_wall, dur_s=time.monotonic() - q.t0_mono,
            point=q.name, status="shed", cache_hit=False, reason=reason,
        )
        q.done.set()


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim serve",
        description="Crash-only simulation service: deadline-budgeted "
        "request queue, pack-coalescing dispatch, backpressure and "
        "graceful drain (see the module docstring for semantics).",
    )
    ap.add_argument(
        "--state-dir", type=Path, required=True, metavar="DIR",
        help="service state dir: serve.tele.jsonl spans, rows.jsonl served "
        "rows, endpoint.json, drain.json — the dir `tpusim slo check "
        "--profile serve` and `tpusim audit` gate",
    )
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument(
        "--port", type=int, default=0,
        help="TCP port (0 = ephemeral; the bound endpoint is printed and "
        "written to <state-dir>/endpoint.json)",
    )
    ap.add_argument(
        "--queue-depth", type=int, default=DEFAULT_QUEUE_DEPTH,
        help="bounded request-queue depth; admission beyond it is a "
        "retryable 503 with depth + ETA (never silent buffering)",
    )
    ap.add_argument(
        "--deadline-s", type=float, default=DEFAULT_DEADLINE_S,
        help="default per-query wall-clock deadline (a request may pass "
        "its own deadline_s); expiry sheds the query, loud",
    )
    ap.add_argument(
        "--serve-engine", default="auto", metavar="ENGINE",
        help="packed engine selector passed to run_grid (default: auto)",
    )
    ap.add_argument(
        "--chaos", type=Path, metavar="PLAN",
        help="chaos drill plan JSON (tpusim.chaos) armed over the serve "
        "seams: serve.accept, serve.dispatch, serve.cache, serve.drain",
    )
    args = ap.parse_args(argv)

    daemon = ServeDaemon(
        args.state_dir, host=args.host, port=args.port,
        queue_depth=args.queue_depth, deadline_s=args.deadline_s,
        engine=args.serve_engine, chaos=args.chaos,
    )
    stop = threading.Event()

    def _on_signal(signum, frame) -> None:
        # JX019: a signal handler only sets the Event; the main loop below
        # does the actual drain outside signal context.
        stop.set()

    signal.signal(signal.SIGTERM, _on_signal)
    signal.signal(signal.SIGINT, _on_signal)
    daemon.start()
    print(
        f"[serve] listening on {daemon.url} (state dir {args.state_dir})",
        flush=True,
    )
    while not stop.wait(0.2):
        pass
    print("[serve] drain requested; finishing accepted queries", flush=True)
    summary = daemon.drain()
    print(
        f"[serve] drained: accepted={summary['accepted']} "
        f"served={summary['served']} shed={summary['shed']} "
        f"rejected={summary['rejected']} clean={summary['clean']}",
        flush=True,
    )
    return 0 if summary["clean"] else 1


if __name__ == "__main__":
    sys.exit(main())
