"""Vectorized sampling primitives.

Reproduces the reference's sampling *semantics* (not its RNG bitstream — runs
are seeded independently there too, via std::random_device, reference
main.cpp:131-134; the cross-validation criterion is distributional):

  * Block intervals: the reference draws an exponential with the mean in
    nanoseconds, rounds to the nearest ns, then *truncates* to milliseconds
    (reference simulation.h:205-210 + xoroshiro128++.h:17-20,36-39) — i.e.
    ``floor`` of an exponential expressed in ms, up to the measure-zero set of
    draws landing within 0.5 ns of an exact ms boundary. The TPU path computes
    ``floor(Exp(mean_ms))`` directly in float32 (TPUs have no native float64):
    the mantissa quantization perturbs a draw by at most ~6e-8 relative, which
    crosses an integer-ms boundary for ~1e-4 of draws, shifting those by 1 ms
    out of ~600 000 — orders of magnitude below the 1e-4 stale-rate
    cross-validation tolerance (see tests/test_statistical.py moments checks).
  * Winner draws: a uniform word compared against cumulative integer
    thresholds ``cumsum(pct) * multiplier`` (reference simulation.h:213-221).
    The reference multiplier maps percent onto uint64; the TPU path uses the
    same construction on uint32 (multiplier ``(2^32-1)//100``), which moves
    each category boundary by < 3e-8 of probability mass.

JAX's threefry generator replaces xoroshiro128++ (reference xoroshiro128++.h:1-40);
it is counter-based, which is what lets every (run, event) draw be independent
of execution order under vmap/scan and across differently-sized chunks.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .config import PERC_MULTIPLIER

__all__ = [
    "winner_thresholds",
    "winner_thresholds32",
    "interval_from_bits",
    "winner_from_bits",
    "winners_from_bits",
    "PERC_MULTIPLIER32",
]

#: uint32 analogue of the reference's percent->u64 multiplier (simulation.h:18).
PERC_MULTIPLIER32 = (2**32 - 1) // 100

#: Clamp on one interval draw in ms; see state.INTERVAL_CAP. At the 600 s
#: reference mean the exceedance probability is e^-223.
_INTERVAL_CAP_MS = float(2**27)


def winner_thresholds(hashrate_pct: np.ndarray) -> np.ndarray:
    """Cumulative uint64 thresholds exactly as the reference accumulates them
    (``i += perc * PERC_MULTIPLIER``, simulation.h:213-221). Used by the
    bit-compatible native backend; the TPU engine uses the uint32 variant."""
    cum: list[int] = []
    total = 0
    for p in hashrate_pct:
        total += int(p) * PERC_MULTIPLIER
        cum.append(total)
    if total > 2**64 - 1:
        raise ValueError("hashrate percentages exceed 100")
    # Element-wise np.uint64() keeps exactness; a direct array cast of Python
    # ints above 2**63-1 goes through float and warns.
    return np.array([np.uint64(c) for c in cum], dtype=np.uint64)


def winner_thresholds32(hashrate_pct: np.ndarray) -> np.ndarray:
    """Cumulative uint32 winner-draw thresholds (TPU-native 32-bit form)."""
    cum = np.cumsum(np.asarray(hashrate_pct, dtype=np.int64)) * PERC_MULTIPLIER32
    if int(cum[-1]) > 2**32 - 1:
        raise ValueError("hashrate percentages exceed 100")
    return cum.astype(np.uint32)


def interval_from_bits(bits: jax.Array, mean_interval_ms) -> jax.Array:
    """Exponential block interval in integer ms (int32) from one uint32 word.

    uniform24 = (u32 >> 8) * 2^-24, expo = -log1p(-u) * mean_ms, floor to ms.
    The 24-bit uniform caps the tail at ~16.6 means (exceedance e^-16.6); the
    explicit clamp keeps int32 time arithmetic overflow-free.
    """
    u = (bits >> jnp.uint32(8)).astype(jnp.float32) * jnp.float32(2.0**-24)
    expo_ms = -jnp.log1p(-u) * jnp.float32(mean_interval_ms)
    return jnp.minimum(expo_ms, _INTERVAL_CAP_MS).astype(jnp.int32)


def winner_from_bits(bits: jax.Array, thresholds: jax.Array) -> jax.Array:
    """Index of the miner who found the block (int32) from one uint32 word.

    First miner whose cumulative threshold strictly exceeds the uniform
    (reference simulation.h:213-221). The reference asserts on the ~96/2^32
    draws that fall past the 100% threshold; we clamp to the last miner.
    """
    w = jnp.sum((thresholds <= bits).astype(jnp.int32))
    return jnp.minimum(w, jnp.int32(thresholds.shape[0] - 1))


def winners_from_bits(bits: jax.Array, thresholds: jax.Array) -> jax.Array:
    """Vectorized :func:`winner_from_bits` over any leading shape of draws:
    one threshold-comparison pass maps a whole chunk's winner words at once
    (the batched-RNG path, SimConfig.rng_batch). Elementwise identical to
    the scalar form — same compare, same sum, same clamp — so the event loop
    consuming these precomputed indices is bit-equal to per-event mapping."""
    w = jnp.sum(
        (thresholds <= bits[..., None]).astype(jnp.int32), axis=-1, dtype=jnp.int32
    )
    # shape[-1], not shape[0]: packed grids pass per-run (R, M) thresholds
    # (tpusim.packed) — the miner axis is always last, and for the 1-D case
    # the two are the same axis.
    return jnp.minimum(w, jnp.int32(thresholds.shape[-1] - 1))
