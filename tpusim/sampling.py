"""Vectorized sampling primitives.

Reproduces the reference's sampling *semantics* (not its RNG bitstream — runs
are seeded independently there too, via std::random_device, reference
main.cpp:131-134; the cross-validation criterion is distributional):

  * Block intervals: exponential with the mean expressed in nanoseconds,
    rounded to the nearest nanosecond, then *truncated* to milliseconds
    (reference simulation.h:205-210 + xoroshiro128++.h:17-20,36-39). The
    truncation shifts the interval mean by ~-0.5 ms; both backends match it.
  * Winner draws: a uint64 uniform compared against cumulative integer
    thresholds ``cumsum(pct) * PERC_MULTIPLIER`` (reference simulation.h:213-221),
    bit-identical threshold arithmetic.

JAX's threefry generator replaces xoroshiro128++ (reference xoroshiro128++.h:1-40);
it is counter-based, which is what lets every (run, event) draw be independent
and order-free under vmap/scan.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

from .config import PERC_MULTIPLIER

__all__ = ["winner_thresholds", "draw_interval_ms", "draw_winner"]


def winner_thresholds(hashrate_pct: np.ndarray) -> np.ndarray:
    """Cumulative uint64 thresholds for the winner draw.

    Matches ``PickFinder``'s accumulator ``i += perc * PERC_MULTIPLIER``
    (reference simulation.h:213-221). Computed with Python ints to avoid any
    intermediate overflow, returned as uint64.
    """
    cum: list[int] = []
    total = 0
    for p in hashrate_pct:
        total += int(p) * PERC_MULTIPLIER
        cum.append(total)
    if total > 2**64 - 1:
        raise ValueError("hashrate percentages exceed 100")
    # Element-wise np.uint64() keeps exactness; a direct array cast of Python
    # ints above 2**63-1 goes through float and warns.
    return np.array([np.uint64(c) for c in cum], dtype=np.uint64)


def draw_interval_ms(key: jax.Array, mean_interval_ns: float) -> jax.Array:
    """One exponential block interval, in integer milliseconds (int64).

    Semantics chain, matching the reference exactly:
    uniform53 = (u64 >> 11) * 2^-53            (xoroshiro128++.h:19)
    expo_ns   = -log1p(-uniform53) * mean_ns   (xoroshiro128++.h:17-20,36-39)
    rounded   = round-to-nearest ns            (simulation.h:207, llround)
    interval  = trunc(rounded / 1e6) ms        (simulation.h:209, duration_cast)

    The only deviation is round-half-to-even (jnp.rint) vs llround's
    half-away-from-zero, which differs only when the product lands on an exact
    .5 ns in float64 — measure-zero for this computation.
    """
    bits = jax.random.bits(key, dtype=jnp.uint64)
    uniform = (bits >> jnp.uint64(11)).astype(jnp.float64) * (2.0**-53)
    expo_ns = -jnp.log1p(-uniform) * mean_interval_ns
    ns = jnp.rint(expo_ns).astype(jnp.int64)
    return ns // 1_000_000


def draw_winner(key: jax.Array, thresholds: jax.Array) -> jax.Array:
    """Index of the miner who found the block (int32).

    First miner whose cumulative threshold strictly exceeds a uint64 uniform
    (reference simulation.h:213-221). The reference asserts on the ~16/2^64
    draws that fall past the 100% threshold; we clamp to the last miner.
    """
    u = jax.random.bits(key, dtype=jnp.uint64)
    w = jnp.sum((thresholds <= u).astype(jnp.int32))
    return jnp.minimum(w, jnp.int32(thresholds.shape[0] - 1))
