"""Version shims for the jax APIs this package uses that moved between
releases. The container image pins jax 0.4.x where ``shard_map`` still lives
in ``jax.experimental`` (with ``check_rep`` instead of ``check_vma``) and
``enable_x64`` in ``jax.experimental``; newer jax exports both from the top
level. Everything in-repo imports them from here so one module owns the
difference.
"""

from __future__ import annotations

import jax

__all__ = ["shard_map", "enable_x64", "set_cpu_device_count"]


def set_cpu_device_count(n: int) -> None:
    """Request ``n`` virtual CPU devices. Older jax (< 0.5) has no
    ``jax_num_cpu_devices`` option — there callers must have set
    ``XLA_FLAGS=--xla_force_host_platform_device_count`` before backend
    init, and this is a no-op."""
    try:
        jax.config.update("jax_num_cpu_devices", n)
    except AttributeError:
        pass

try:
    from jax import shard_map as _new_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _new_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_vma=check_vma
        )

except ImportError:
    from jax.experimental.shard_map import shard_map as _old_shard_map

    def shard_map(f, *, mesh, in_specs, out_specs, check_vma: bool = True):
        return _old_shard_map(
            f, mesh=mesh, in_specs=in_specs, out_specs=out_specs, check_rep=check_vma
        )


if hasattr(jax, "enable_x64"):

    def enable_x64(new_val: bool = True):
        return jax.enable_x64(new_val)

else:
    from jax.experimental import enable_x64 as _old_enable_x64

    def enable_x64(new_val: bool = True):
        return _old_enable_x64(new_val)
