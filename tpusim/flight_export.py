"""Host-side decode and export of the device flight recorder (``tpusim
trace``).

Takes the per-run ring buffers a flight-enabled ``run_batch`` returns
(``flight_buf`` int32 [runs, capacity, N_FIELDS], ``flight_count`` int32
[runs] — see :mod:`tpusim.flight` for the row semantics) and turns them into:

  * a **JSONL event log** — one line per event, sorted by (run, seq), with
    stable keys ``{"run", "seq", "kind", "t_ms", "miner", "height",
    "depth"}`` — the cross-backend oracle format: the native C++ backend's
    event sequence for the same seed (``rng="xoroshiro"`` draws
    bit-compatibly with it) diffs line-by-line against this file;
  * a **Chrome-trace / Perfetto JSON** timeline — one process per run, one
    track (thread) per miner, instant events stamped at absolute simulation
    milliseconds — loadable in ``ui.perfetto.dev`` or ``chrome://tracing``
    and correlated to the ``--telemetry`` span ledger through the same
    ``run_id`` recorded in ``otherData``.

Ring overflow is explicit: ``count`` keeps the true event total, so runs
whose event count exceeded the capacity report ``dropped = count -
capacity`` (the ring keeps the NEWEST rows) instead of silently truncating.

CLI::

    python -m tpusim trace --runs 4 --days 2 --flight-capacity 1024 \
        --trace-out artifacts/telemetry/sample.trace.json \
        --events-out /tmp/events.jsonl

Cross-backend workflow: ``--backend cpp`` emits the SAME event-log schema
from the native backend (native/simcore.cpp writes it directly — the oracle
side of the README diff recipe), and ``tpusim trace diff A.jsonl B.jsonl``
is the structured comparator: first divergent (run, seq) row with both
sides printed, per-kind event-count deltas, nonzero exit on divergence —
the recipe's manual ``diff`` replaced by a localizer::

    python -m tpusim trace --rng xoroshiro --seed 7 ... --events-out a.jsonl
    python -m tpusim trace --backend cpp --seed 7 ... --events-out b.jsonl
    python -m tpusim trace diff a.jsonl b.jsonl
"""

from __future__ import annotations

import dataclasses
import json
import sys
from pathlib import Path
from typing import Any

import numpy as np

from .flight import FLIGHT_TIME_BASE, KIND_NAMES, N_FIELDS
# The schema gate and artifact writer live in the jax-free tracing module
# (the orchestration timeline shares both and must not pull a backend in);
# re-exported here for the existing consumers (tests, CI, harvest).
from .tracing import _write_artifact, validate_perfetto

__all__ = [
    "FlightLog", "decode_flight", "decode_flight_packed", "events_jsonl",
    "perfetto_trace",
    "validate_perfetto", "TraceDiff", "load_events_jsonl", "diff_event_logs",
    "main",
]


@dataclasses.dataclass
class FlightLog:
    """Decoded flight events of one or more batches."""

    #: One dict per surviving event, sorted by (run, seq): run (global run
    #: index), seq (event number within the run, 0-based over ALL events
    #: including dropped ones), kind, t_ms (absolute simulation ms, int),
    #: miner, height, depth.
    events: list[dict]
    #: global run index -> rows lost to ring overflow (0 entries omitted).
    dropped: dict[int, int]
    capacity: int

    def extend(self, other: "FlightLog") -> None:
        self.events.extend(other.events)
        self.dropped.update(other.dropped)


def decode_flight(sums: dict[str, Any], *, start: int = 0) -> FlightLog:
    """Decode one ``run_batch`` output; ``start`` is the batch's first global
    run index (the recorder never stores run ids — the vmapped position plus
    the batch offset IS the identity, same convention as ``make_run_keys``)."""
    buf = np.asarray(sums["flight_buf"])
    cnt = np.asarray(sums["flight_count"])
    runs, capacity, fields = buf.shape
    if fields != N_FIELDS:
        raise ValueError(f"flight_buf has {fields} fields, expected {N_FIELDS}")
    events: list[dict] = []
    dropped: dict[int, int] = {}
    for r in range(runs):
        n = int(cnt[r])
        if n > capacity:
            dropped[start + r] = n - capacity
        # Surviving events are the newest min(n, capacity): sequence numbers
        # [n - kept, n); event e sits at ring slot e % capacity.
        for e in range(n - min(n, capacity), n):
            row = buf[r, e % capacity]
            events.append({
                "run": start + r,
                "seq": e,
                "kind": KIND_NAMES[int(row[0])],
                "t_ms": int(row[4]) * FLIGHT_TIME_BASE + int(row[5]),
                "miner": int(row[1]),
                "height": int(row[2]),
                "depth": int(row[3]),
            })
    return FlightLog(events=events, dropped=dropped, capacity=capacity)


def decode_flight_packed(
    sums: dict[str, Any], pieces: list[tuple[int, int, int]]
) -> dict[int, FlightLog]:
    """Decode one PACKED dispatch's rings (tpusim.packed): the runs axis of
    ``flight_buf``/``flight_count`` holds the dispatch's pieces back to
    back, so ``pieces`` — ``(point, start, count)`` triples in pack order,
    the dispatch's own layout — is the pack-position → (point, run)
    mapping. Each piece's slice decodes exactly like a sequential batch
    with the piece's global run offset (``decode_flight(..., start=)``), so
    run ids round-trip and the per-point logs diff cleanly against a
    sequential ``tpusim trace``. Pad lanes sit past the last piece and are
    never decoded. Returns ``{point: FlightLog}`` for the points this
    dispatch touched."""
    buf = np.asarray(sums["flight_buf"])
    cnt = np.asarray(sums["flight_count"])
    logs: dict[int, FlightLog] = {}
    off = 0
    for point, start, count in pieces:
        sl = slice(off, off + count)
        log = decode_flight(
            {"flight_buf": buf[sl], "flight_count": cnt[sl]}, start=start
        )
        if point in logs:
            logs[point].extend(log)
        else:
            logs[point] = log
        off += count
    return logs


def events_jsonl(events: list[dict]) -> str:
    """The diffable event-log text: one JSON object per line, key order
    fixed by the event dicts (insertion order), sorted by (run, seq)."""
    ordered = sorted(events, key=lambda e: (e["run"], e["seq"]))
    return "".join(json.dumps(e) + "\n" for e in ordered)


def perfetto_trace(
    events: list[dict],
    *,
    n_miners: int,
    run_id: str | None = None,
    meta: dict[str, Any] | None = None,
) -> dict:
    """Chrome-trace JSON: pid = run, tid = miner track, instant events at
    absolute sim time (``ts`` is microseconds per the trace-event spec, so
    1 trace second renders as 1 simulated millisecond x 1000)."""
    tev: list[dict] = []
    runs = sorted({e["run"] for e in events})
    for r in runs:
        tev.append({
            "ph": "M", "name": "process_name", "pid": r,
            "args": {"name": f"run {r}"},
        })
        for m in range(n_miners):
            tev.append({
                "ph": "M", "name": "thread_name", "pid": r, "tid": m,
                "args": {"name": f"miner {m}"},
            })
    for e in sorted(events, key=lambda e: (e["run"], e["seq"])):
        tev.append({
            "name": e["kind"],
            "ph": "i",
            "s": "t",  # thread-scoped instant: one tick on the miner's track
            "ts": e["t_ms"] * 1000,
            "pid": e["run"],
            "tid": e["miner"],
            "args": {"seq": e["seq"], "height": e["height"], "depth": e["depth"]},
        })
    other: dict[str, Any] = {"tool": "tpusim trace"}
    if run_id is not None:
        other["run_id"] = run_id
    if meta:
        other.update(meta)
    return {"traceEvents": tev, "displayTimeUnit": "ms", "otherData": other}


@dataclasses.dataclass
class TraceDiff:
    """Structured comparison of two event logs (see :func:`diff_event_logs`)."""

    #: (run, seq) key of the first divergent row, or None when identical.
    first_key: tuple[int, int] | None
    #: The divergent rows themselves (None on the side missing the key).
    first_a: dict | None
    first_b: dict | None
    #: Per-kind event counts of each log.
    kinds_a: dict[str, int]
    kinds_b: dict[str, int]
    n_a: int
    n_b: int

    @property
    def divergent(self) -> bool:
        return self.first_key is not None

    def render(self, name_a: str = "A", name_b: str = "B") -> str:
        out = [f"trace diff: {name_a} ({self.n_a} events) vs {name_b} "
               f"({self.n_b} events)"]
        kinds = sorted(set(self.kinds_a) | set(self.kinds_b))
        for kind in kinds:
            na, nb = self.kinds_a.get(kind, 0), self.kinds_b.get(kind, 0)
            delta = f"{nb - na:+d}" if na != nb else "=="
            out.append(f"  {kind:8s} {na:8d} {nb:8d}  {delta}")
        if not self.divergent:
            out.append("identical event sequences")
        else:
            run, seq = self.first_key
            out.append(f"FIRST DIVERGENCE at (run {run}, seq {seq}):")
            out.append(f"  {name_a}: " + (json.dumps(self.first_a) if self.first_a
                                          else "<no row>"))
            out.append(f"  {name_b}: " + (json.dumps(self.first_b) if self.first_b
                                          else "<no row>"))
        return "\n".join(out) + "\n"


def load_events_jsonl(path: Path) -> list[dict]:
    """Parse an event log STRICTLY (unlike telemetry.load_spans): these files
    are freshly produced oracle inputs, and a torn or foreign line in one is
    itself a divergence that must fail loud, not be skipped."""
    events = []
    for i, line in enumerate(Path(path).read_text().splitlines()):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError as e:
            raise ValueError(f"{path}:{i + 1}: unparseable event line ({e})") from None
        if not isinstance(row, dict) or "run" not in row or "seq" not in row:
            raise ValueError(f"{path}:{i + 1}: not an event row: {line[:120]!r}")
        events.append(row)
    return events


def diff_event_logs(a: list[dict], b: list[dict]) -> TraceDiff:
    """Compare two event logs row-by-row in (run, seq) order: the first key
    where the rows differ — or exist on one side only — is the divergence
    point (everything after the first divergent event of a run is causally
    suspect, so ONE localized row beats a full dump)."""
    key = lambda e: (int(e["run"]), int(e["seq"]))
    a = sorted(a, key=key)
    b = sorted(b, key=key)
    kinds_a: dict[str, int] = {}
    kinds_b: dict[str, int] = {}
    for e in a:
        kinds_a[str(e.get("kind"))] = kinds_a.get(str(e.get("kind")), 0) + 1
    for e in b:
        kinds_b[str(e.get("kind"))] = kinds_b.get(str(e.get("kind")), 0) + 1
    first_key = first_a = first_b = None
    ia = ib = 0
    while ia < len(a) or ib < len(b):
        ka = key(a[ia]) if ia < len(a) else None
        kb = key(b[ib]) if ib < len(b) else None
        if ka is not None and (kb is None or ka < kb):
            first_key, first_a, first_b = ka, a[ia], None
            break
        if kb is not None and (ka is None or kb < ka):
            first_key, first_a, first_b = kb, None, b[ib]
            break
        if a[ia] != b[ib]:
            first_key, first_a, first_b = ka, a[ia], b[ib]
            break
        ia += 1
        ib += 1
    return TraceDiff(
        first_key=first_key, first_a=first_a, first_b=first_b,
        kinds_a=kinds_a, kinds_b=kinds_b, n_a=len(a), n_b=len(b),
    )


def diff_main(argv: list[str] | None = None) -> int:
    """``tpusim trace diff``: exit 0 on identical logs, 1 on divergence
    (with the first divergent row localized), 2 on unreadable input."""
    import argparse

    ap = argparse.ArgumentParser(
        prog="tpusim trace diff",
        description="Structured diff of two flight-recorder JSONL event logs.",
    )
    ap.add_argument("a", type=Path, help="first event log (e.g. the JAX engine's)")
    ap.add_argument("b", type=Path, help="second event log (e.g. the native backend's)")
    args = ap.parse_args(argv)
    try:
        ev_a = load_events_jsonl(args.a)
        ev_b = load_events_jsonl(args.b)
    except (OSError, ValueError) as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    diff = diff_event_logs(ev_a, ev_b)
    print(diff.render(str(args.a), str(args.b)), end="")
    return 1 if diff.divergent else 0


def main(argv: list[str] | None = None) -> int:
    """``tpusim trace``: run a (small) simulation with the flight recorder on
    and export the ring as Perfetto JSON + optional JSONL event log. Accepts
    every run flag of ``tpusim`` (config file, roster flags, --engine, ...);
    runs unsharded on purpose — event traces are a debugging tool for runs
    small enough to read, and per-run identity must stay trivially stable."""
    from .cli import build_parser, config_from_args

    if argv and argv[0] == "diff":
        # `tpusim trace diff A.jsonl B.jsonl`: compare two already-exported
        # event logs instead of producing one.
        return diff_main(argv[1:])
    if argv and argv[0] == "timeline":
        # `tpusim trace timeline STATE_DIR`: the cross-process orchestration
        # timeline (tpusim.tracing). Normally dispatched jax-free straight
        # from the umbrella CLI; this branch covers direct module use.
        from .tracing import timeline_main

        return timeline_main(argv[1:])

    p = build_parser()
    p.prog = "tpusim trace"
    p.description = "Run with the event flight recorder on and export the timeline."
    p.add_argument(
        "--flight-capacity", type=int, default=None,
        help="per-run ring rows to keep (newest win; dropped counts "
        "reported); default: the config file's flight_capacity, else 1024",
    )
    p.add_argument(
        "--trace-out", type=Path, default=None,
        help="Perfetto / chrome-trace JSON output (load in ui.perfetto.dev; "
        "default flight.trace.json)",
    )
    p.add_argument(
        "--events-out", type=Path, default=None,
        help="also write the JSONL event log here (cross-backend diffable)",
    )
    args = p.parse_args(argv)
    if args.backend == "cpp":
        # The native producer (native/simcore.cpp simcore_run_events): the
        # oracle side of the diff recipe, same JSONL schema, no JAX import.
        if args.events_out is None:
            raise SystemExit(
                "error: --backend cpp emits the JSONL event log only; "
                "pass --events-out"
            )
        if args.trace_out is not None:
            raise SystemExit(
                "error: --trace-out renders the device flight ring; the cpp "
                "producer writes the diffable event log only (--events-out)"
            )
        if args.flight_capacity is not None:
            raise SystemExit(
                "error: --flight-capacity sizes the device ring; the native "
                "producer keeps every event"
            )
        try:
            config = config_from_args(args)
        except ValueError as e:
            raise SystemExit(f"error: {e}") from None
        from .backend.cpp import run_events_cpp

        n_events = run_events_cpp(config, args.events_out)
        if not args.quiet:
            print(
                f"[trace] native backend wrote {n_events} events from "
                f"{config.runs} run(s) -> {args.events_out}"
            )
        return 0
    if args.flight_capacity is not None and args.flight_capacity < 1:
        raise SystemExit("error: --flight-capacity must be >= 1 for tracing")
    if args.trace_out is None:
        args.trace_out = Path("flight.trace.json")
    try:
        config = config_from_args(args)
    except ValueError as e:
        raise SystemExit(f"error: {e}") from None
    # Flag wins over config file, config file over the 1024 default — a
    # config that sized its own ring must not be clobbered by the default.
    capacity = args.flight_capacity or config.flight_capacity or 1024
    config = dataclasses.replace(config, flight_capacity=capacity)

    from .runner import make_engine
    from .telemetry import new_run_id

    run_id = new_run_id()
    prefer = None if args.engine == "auto" else (args.engine == "pallas")
    eng = make_engine(
        config, None, prefer_pallas=prefer,
        tile_runs=args.tile_runs, step_block=args.step_block,
    )
    log = FlightLog(events=[], dropped={}, capacity=capacity)
    tele_totals: dict[str, int] = {"stale_events": 0}
    done = 0
    while done < config.runs:
        n = min(config.batch_size, config.runs - done)
        out = eng.run_batch(eng.make_keys(done, n))
        log.extend(decode_flight(out, start=done))
        tele_totals["stale_events"] += int(out["tele_stale_events_sum"])
        done += n

    # Sort once; the exporters' own (run, seq) sorts are then O(n) no-ops.
    log.events.sort(key=lambda e: (e["run"], e["seq"]))
    m = config.network.n_miners
    trace = perfetto_trace(
        log.events, n_miners=m, run_id=run_id,
        meta={
            "config": json.loads(config.to_json()),
            "dropped": {str(k): v for k, v in sorted(log.dropped.items())},
        },
    )
    validate_perfetto(trace)
    _write_artifact(args.trace_out, json.dumps(trace))
    if args.events_out is not None:
        _write_artifact(args.events_out, events_jsonl(log.events))
    from .provenance import emit_lineage, lineage_armed, sha256_file

    if lineage_armed():
        # Exported files are addressed by their bytes on disk (they are not
        # JSONL rows an auditor could re-hash from content) — the record
        # pins each artifact's sha256 next to the run_id its spans carry.
        emit_lineage(
            "flight_export",
            content={"kind": "flight_export",
                     "sha256": sha256_file(args.trace_out)},
            path=str(args.trace_out), run_id=run_id, runs=config.runs,
            events=len(log.events),
        )
        if args.events_out is not None:
            emit_lineage(
                "flight_export",
                content={"kind": "flight_export",
                         "sha256": sha256_file(args.events_out)},
                path=str(args.events_out), run_id=run_id,
                events=len(log.events),
            )
    if args.telemetry:
        # Correlate with the span ledger: the trace span carries the SAME
        # run_id as the exported file's otherData.
        from .telemetry import TelemetryRecorder

        rec = TelemetryRecorder(args.telemetry, run_id=run_id)
        rec.emit(
            "trace", runs=config.runs, events=len(log.events),
            dropped=sum(log.dropped.values()), capacity=capacity,
            trace_out=str(args.trace_out),
        )
        rec.close()

    if not args.quiet:
        stale_rows = sum(1 for e in log.events if e["kind"] == "stale")
        print(
            f"[trace] {len(log.events)} events from {config.runs} runs "
            f"({len(log.dropped)} run(s) overflowed, "
            f"{sum(log.dropped.values())} rows dropped; "
            f"{stale_rows} stale rows vs counter {tele_totals['stale_events']}) "
            f"-> {args.trace_out} (run_id {run_id}; open in ui.perfetto.dev)"
        )
        if log.dropped:
            print(
                "[trace] raise --flight-capacity above the per-run event "
                "count to keep every event"
            )
    return 0


if __name__ == "__main__":
    sys.exit(main())
