"""The O(1)-state chain automaton — the heart of the TPU re-design.

The reference materializes a growing ``std::vector<Block>`` chain per miner and
resolves consensus by structural chain comparison (reference simulation.h:41-202,
main.cpp:68-112). Growing per-run chains are a non-starter on TPU (52k blocks x
2^20 runs cannot be stored, and dynamic shapes defeat XLA). Instead every chain
is collapsed into fixed-shape integers per (run, miner):

  * ``height``            — own chain length, genesis excluded.
  * ``n_private``         — trailing private (selfish, unrevealed) own blocks;
                            the paper's ``privateBranchLen`` and the reference's
                            ``SelfishBlocks()`` (simulation.h:105-115).
  * arrival *groups*      — published-but-not-yet-propagated trailing own
                            blocks, run-length encoded as up to ``K`` (arrival,
                            count) pairs, sorted by arrival. These carry the
                            information of ``UnpublishedBlocks``/``NextArrival``
                            (simulation.h:79-102). Arrived blocks are flushed
                            into ``base_tip_arrival``.
  * ``base_tip_arrival``  — arrival time of the highest *arrived* block; the
                            first-seen tiebreak key (main.cpp:74-76).
  * ``cp[i, j, o]``       — the consensus sufficient statistic: the number of
                            blocks owned by miner ``o`` inside the common prefix
                            of miner ``i``'s and miner ``j``'s chains. This one
                            tensor replaces every structural chain comparison:
                            - reorg stale accounting (simulation.h:124-142):
                              blocks of ``i`` popped when adopting best owner
                              ``b``'s chain = ``cp[i,i,i] - cp[i,b,i]``;
                            - final per-miner stats against the best chain
                              (main.cpp:22-30): ``i``'s blocks in ``b``'s
                              published chain = ``cp[b,b,i]`` minus ``b``'s
                              unpublished tail when ``i == b``.
                            The update rules below are closed under the two
                            events of the system (own-append, adopt-published),
                            so the representation is exact — see
                            tests/test_state_equivalence.py which checks it
                            against a literal chain simulator on random runs.

A cheaper pairwise variant (``own_above[i,j]``, ``own_in[i,j]``, "fast" mode)
drops the 3-index tensor; it is exact except when a miner adopts a chain that
contains its *own* blocks above that chain's fork point with a *third* miner
that later wins — a multi-branch geometry with probability O((prop/interval)^2)
per race in honest networks, far below the 1e-4 stale-rate tolerance. Selfish
configurations route to "exact" mode automatically (deep reorgs there make the
third-party term first-order).

Everything in this module operates on a single unbatched run; the engine vmaps
over runs and lax.scans over events.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .config import SimConfig
from .sampling import winner_thresholds

# Sentinel for "no arrival" (empty group slot / private blocks). Kept well below
# int64 max so that comparisons never sit at the overflow edge. The reference
# uses milliseconds::max for private blocks (simulation.h:20).
INF_TIME = jnp.int64(2**62)

I32 = jnp.int32
I64 = jnp.int64


class SimParams(NamedTuple):
    """Static per-network arrays, closed over by the jitted step."""

    thresholds: jax.Array  # uint64 [M] cumulative winner-draw thresholds
    prop_ms: jax.Array  # int64 [M]
    selfish: jax.Array  # bool [M]
    mean_interval_ns: float
    duration_ms: int


def make_params(config: SimConfig) -> SimParams:
    net = config.network
    return SimParams(
        thresholds=jnp.asarray(winner_thresholds(np.array([m.hashrate_pct for m in net.miners]))),
        prop_ms=jnp.asarray([m.propagation_ms for m in net.miners], dtype=I64),
        selfish=jnp.asarray([m.selfish for m in net.miners], dtype=jnp.bool_),
        mean_interval_ns=net.block_interval_s * 1e9,
        duration_ms=config.duration_ms,
    )


class SimState(NamedTuple):
    """Per-run simulation state (one element of the vmapped batch)."""

    t: jax.Array  # int64 [] current simulation time (ms)
    next_block_time: jax.Array  # int64 [] absolute time of the next block find
    best_height_prev: jax.Array  # int32 [] best published height after last notify
    height: jax.Array  # int32 [M] own chain length (genesis excluded)
    n_private: jax.Array  # int32 [M] trailing private selfish blocks
    stale: jax.Array  # int32 [M] own blocks reorged out (simulation.h:133)
    base_tip_arrival: jax.Array  # int64 [M] arrival of highest arrived block
    group_arrival: jax.Array  # int64 [M, K] in-flight own block groups (sorted)
    group_count: jax.Array  # int32 [M, K]
    overflow: jax.Array  # int32 [] group-slot overflow events (diagnostic)
    cp: Optional[jax.Array]  # int32 [M, M, M] common-prefix owner counts (exact mode)
    own_above: Optional[jax.Array]  # int32 [M, M] own blocks above lca (fast mode)
    own_in: Optional[jax.Array]  # int32 [M, M] own_in[j, i] = i's blocks in j's chain


def init_state(n_miners: int, group_slots: int, exact: bool) -> SimState:
    m, k = n_miners, group_slots
    return SimState(
        t=jnp.zeros((), I64),
        next_block_time=jnp.zeros((), I64),
        best_height_prev=jnp.zeros((), I32),
        height=jnp.zeros((m,), I32),
        n_private=jnp.zeros((m,), I32),
        stale=jnp.zeros((m,), I32),
        base_tip_arrival=jnp.zeros((m,), I64),
        group_arrival=jnp.full((m, k), INF_TIME, I64),
        group_count=jnp.zeros((m, k), I32),
        overflow=jnp.zeros((), I32),
        cp=jnp.zeros((m, m, m), I32) if exact else None,
        own_above=None if exact else jnp.zeros((m, m), I32),
        own_in=None if exact else jnp.zeros((m, m), I32),
    )


def _push_groups(
    arr: jax.Array,
    cnt: jax.Array,
    new_arrival: jax.Array,
    new_count: jax.Array,
    do: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Append an (arrival, count) group per miner where ``do`` is set.

    Groups stay sorted because every push for a given miner uses a strictly
    later stamp time with the same propagation delay. Equal-arrival pushes
    merge into the last group (the publish-both race of simulation.h:66-69
    produces two blocks with one arrival). A full buffer merges into the last
    slot, keeping counts exact and arrival = the later one; this bounded-memory
    fallback is counted in the returned overflow increment.
    """
    m, k = arr.shape
    n = jnp.sum(cnt > 0, axis=-1, dtype=I32)  # [M]
    last_idx = jnp.maximum(n - 1, 0)
    last_arrival = jnp.take_along_axis(arr, last_idx[:, None], axis=-1)[:, 0]
    merge = do & (n > 0) & (last_arrival == new_arrival)
    overflowed = do & ~merge & (n == k)
    write_idx = jnp.where(merge | overflowed, last_idx, jnp.minimum(n, k - 1))
    onehot = (jnp.arange(k)[None, :] == write_idx[:, None]) & do[:, None]
    arr_new = jnp.where(onehot, new_arrival[:, None], arr)
    accum = (merge | overflowed)[:, None]
    cnt_new = jnp.where(onehot, jnp.where(accum, cnt + new_count[:, None], new_count[:, None]), cnt)
    return arr_new, cnt_new, jnp.sum(overflowed, dtype=I32)


def _flush_groups(
    arr: jax.Array, cnt: jax.Array, base_tip: jax.Array, t: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Move arrived groups (arrival <= t) into the base, compacting the buffer.

    The arrived set is a prefix (groups are sorted), and the new base tip is
    the arrival of the last flushed group — the chain-highest arrived block,
    which is exactly the published-chain tip the first-seen rule compares
    (main.cpp:74-76)."""
    m, k = arr.shape
    arrived = arr <= t
    n_f = jnp.sum(arrived, axis=-1, dtype=I32)
    flushed_tip = jnp.take_along_axis(arr, jnp.maximum(n_f - 1, 0)[:, None], axis=-1)[:, 0]
    new_base = jnp.where(n_f > 0, flushed_tip, base_tip)
    idx = jnp.arange(k)[None, :] + n_f[:, None]
    valid = idx < k
    gidx = jnp.minimum(idx, k - 1)
    arr_new = jnp.where(valid, jnp.take_along_axis(arr, gidx, axis=-1), INF_TIME)
    cnt_new = jnp.where(valid, jnp.take_along_axis(cnt, gidx, axis=-1), 0)
    return arr_new, cnt_new, new_base


def found_block(state: SimState, params: SimParams, w: jax.Array) -> SimState:
    """Miner ``w`` finds a block at ``state.t``.

    Semantics of ``Miner::FoundBlock`` (reference simulation.h:62-76):
      * honest: append an own block arriving at ``t + propagation``;
      * selfish, not in a 1-block race: append a private block;
      * selfish winning a 1-block race (exactly one private block and the best
        published chain matched our length at the last notify): publish the
        private block *and* the new one, both arriving at ``t + propagation``.
    """
    m = state.height.shape[0]
    onehot_w = jnp.arange(m) == w
    is_selfish = params.selfish[w]
    is_race = is_selfish & (state.n_private[w] == 1) & (state.best_height_prev == state.height[w])
    private_append = is_selfish & ~is_race

    arrival = jnp.full((m,), state.t, I64) + params.prop_ms
    push_count = jnp.where(is_race, I32(2), I32(1))
    arr, cnt, over = _push_groups(
        state.group_arrival,
        state.group_count,
        arrival,
        jnp.full((m,), push_count, I32),
        onehot_w & ~private_append,
    )
    n_private = state.n_private + jnp.where(
        onehot_w, jnp.where(private_append, I32(1), jnp.where(is_race, I32(-1), I32(0))), I32(0)
    )
    height = state.height + onehot_w.astype(I32)

    cp = state.cp
    own_above, own_in = state.own_above, state.own_in
    if cp is not None:
        cp = cp.at[w, w, w].add(1)
    else:
        # The new block is above every lca with other miners.
        own_above = own_above + (onehot_w[:, None] & ~onehot_w[None, :]).astype(I32)
        own_in = own_in.at[w, w].add(1)

    return state._replace(
        height=height,
        n_private=n_private,
        group_arrival=arr,
        group_count=cnt,
        overflow=state.overflow + over,
        cp=cp,
        own_above=own_above,
        own_in=own_in,
    )


def _best_chain(
    height: jax.Array, n_private: jax.Array, group_count: jax.Array, tip: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Longest published chain with the first-seen tiebreak (main.cpp:68-82).

    Assumes groups hold only unarrived blocks (call after flushing). Returns
    (owner index, published height per miner, best height, best tip arrival).
    Ties on both height and tip arrival resolve to the lowest miner index,
    matching the reference's scan order with strict comparisons.
    """
    pub_height = height - n_private - jnp.sum(group_count, axis=-1, dtype=I32)
    best_h = jnp.max(pub_height)
    cand = pub_height == best_h
    tip_masked = jnp.where(cand, tip, INF_TIME)
    best_tip = jnp.min(tip_masked)
    b = jnp.argmax(cand & (tip_masked == best_tip)).astype(I32)
    return b, pub_height, best_h, best_tip


def notify(state: SimState, params: SimParams) -> SimState:
    """One best-chain recompute + notify-all sweep at ``state.t``.

    Mirrors one iteration tail of the reference event loop (main.cpp:160-171):
    flush arrivals, find the best published chain, let every selfish miner
    selectively reveal (simulation.h:149-174), then let every miner reorg to
    the best chain if strictly longer (simulation.h:124-142). The reference
    iterates miners sequentially against one fixed best-chain span; no miner's
    notify can affect another's within a sweep, so the vectorized simultaneous
    update is equivalent.
    """
    m = state.height.shape[0]
    arr, cnt, base_tip = _flush_groups(
        state.group_arrival, state.group_count, state.base_tip_arrival, state.t
    )
    b, pub_height, best_h, best_tip = _best_chain(state.height, state.n_private, cnt, base_tip)

    # --- Selfish reveal (simulation.h:149-174). Runs before reorg; only for
    # miners whose chain is at least as long as the best published one.
    lead = state.height - best_h
    sc = state.n_private
    can_reveal = params.selfish & (lead >= 0) & (sc > lead)
    reveal_n = jnp.where((sc > 1) & (lead == 1), sc, sc - lead)
    arr, cnt, over = _push_groups(
        arr, cnt, jnp.full((m,), state.t, I64) + params.prop_ms, reveal_n, can_reveal
    )
    n_private = jnp.where(can_reveal, sc - reveal_n, sc)

    # --- Reorg (simulation.h:124-142): adopt the best chain when strictly
    # longer than the *full* local chain (private blocks included).
    adopt = best_h > state.height
    unpub_b = state.height[b] - best_h

    cp = state.cp
    own_above, own_in = state.own_above, state.own_in
    if cp is not None:
        own_self = cp[jnp.arange(m), jnp.arange(m), jnp.arange(m)]
        own_common_b = cp[jnp.arange(m), b, jnp.arange(m)]
        stale = state.stale + jnp.where(adopt, own_self - own_common_b, 0)

        # Closed-form cp update: every adopter's chain becomes b's published
        # chain; see module docstring for the case analysis.
        cpb = cp[b]  # [M, M] common-prefix owner counts of b with each j
        cpb_pub = cp[b, b, :] - unpub_b * (jnp.arange(m) == b).astype(I32)
        is_b_i = (jnp.arange(m) == b)[:, None]
        is_b_j = (jnp.arange(m) == b)[None, :]
        a_i = adopt[:, None]
        a_j = adopt[None, :]
        cond_pub = (a_i & (a_j | is_b_j)) | (is_b_i & a_j)
        cond_bj = a_i & ~a_j & ~is_b_j
        cond_bi = ~a_i & ~is_b_i & a_j
        cp = jnp.where(
            cond_pub[:, :, None],
            cpb_pub[None, None, :],
            jnp.where(cond_bj[:, :, None], cpb[None, :, :], jnp.where(cond_bi[:, :, None], cpb[:, None, :], cp)),
        )
    else:
        stale = state.stale + jnp.where(adopt, own_above[:, b], 0)
        # Adopter rows: own blocks above any lca become 0 (chain is b_pub, a
        # prefix-free copy); columns toward adopters copy the column toward b.
        oa = jnp.where(adopt[None, :], own_above[:, b][:, None], own_above)
        own_above = jnp.where(adopt[:, None], 0, oa)
        onehot_b = (jnp.arange(m) == b).astype(I32)
        own_in_bpub = own_in[b, :] - unpub_b * onehot_b
        own_in = jnp.where(adopt[:, None], own_in_bpub[None, :], own_in)

    height = jnp.where(adopt, best_h, state.height)
    n_private = jnp.where(adopt, 0, n_private)
    arr = jnp.where(adopt[:, None], INF_TIME, arr)
    cnt = jnp.where(adopt[:, None], 0, cnt)
    base_tip = jnp.where(adopt, best_tip, base_tip)

    return state._replace(
        best_height_prev=best_h.astype(I32),
        height=height,
        n_private=n_private,
        stale=stale,
        base_tip_arrival=base_tip,
        group_arrival=arr,
        group_count=cnt,
        overflow=state.overflow + over,
        cp=cp,
        own_above=own_above,
        own_in=own_in,
    )


def earliest_arrival(state: SimState) -> jax.Array:
    """Earliest pending block arrival strictly after ``state.t``, INF_TIME if
    none (reference main.cpp:99-112 + simulation.h:92-102, whose NextArrival
    only reports arrivals > cur_time)."""
    return jnp.min(jnp.where(state.group_arrival > state.t, state.group_arrival, INF_TIME))


def final_stats(state: SimState, params: SimParams) -> dict[str, jax.Array]:
    """Per-miner stats against the best chain at ``duration`` (main.cpp:13-41,
    185-191): blocks found in the best chain, share of the best chain, and
    stale blocks per found block. All ratios are per-run; the runner averages
    ratios across runs exactly like the reference (main.cpp:214-216,230-231).
    """
    m = state.height.shape[0]
    t_end = jnp.asarray(params.duration_ms, I64)
    unarrived = jnp.sum(state.group_count * (state.group_arrival > t_end), axis=-1, dtype=I32)
    pub_height = state.height - state.n_private - unarrived
    arrived_mask = state.group_arrival <= t_end
    last_arrived = jnp.max(jnp.where(arrived_mask, state.group_arrival, -1), axis=-1)
    tip = jnp.maximum(state.base_tip_arrival, last_arrived)

    best_h = jnp.max(pub_height)
    cand = pub_height == best_h
    tip_masked = jnp.where(cand, tip, INF_TIME)
    b = jnp.argmax(cand & (tip_masked == jnp.min(tip_masked)))

    own_in_b = state.cp[b, b, :] if state.cp is not None else state.own_in[b, :]
    unpub_b = state.height[b] - pub_height[b]
    found = (own_in_b - unpub_b * (jnp.arange(m) == b).astype(I32)).astype(jnp.int64)
    denom = jnp.maximum(best_h, 1).astype(jnp.float64)
    share = jnp.where(found > 0, found / denom, 0.0)
    stale_rate = jnp.where(found > 0, state.stale / jnp.maximum(found, 1), 0.0)
    return {
        "blocks_found": found,
        "blocks_share": share,
        "stale_rate": stale_rate,
        "stale_blocks": state.stale.astype(jnp.int64),
        "best_height": best_h.astype(jnp.int64),
        "overflow": state.overflow.astype(jnp.int64),
        "truncated": state.t < t_end,
    }
