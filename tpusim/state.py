"""The O(1)-state chain automaton — the heart of the TPU re-design.

The reference materializes a growing ``std::vector<Block>`` chain per miner and
resolves consensus by structural chain comparison (reference simulation.h:41-202,
main.cpp:68-112). Growing per-run chains are a non-starter on TPU (52k blocks x
2^20 runs cannot be stored, and dynamic shapes defeat XLA). Instead every chain
is collapsed into fixed-shape integers per (run, miner):

  * ``height``            — own chain length, genesis excluded.
  * ``n_private``         — trailing private (selfish, unrevealed) own blocks;
                            the paper's ``privateBranchLen`` and the reference's
                            ``SelfishBlocks()`` (simulation.h:105-115).
  * arrival *groups*      — published-but-not-yet-propagated trailing own
                            blocks, run-length encoded as up to ``K`` (arrival,
                            count) pairs, sorted by arrival. These carry the
                            information of ``UnpublishedBlocks``/``NextArrival``
                            (simulation.h:79-102). Arrived blocks are flushed
                            into ``base_tip_arrival``.
  * ``base_tip_arrival``  — arrival time of the highest *arrived* block; the
                            first-seen tiebreak key (main.cpp:74-76).
  * ``cp[i, j, o]``       — (exact mode) the consensus sufficient statistic:
                            the number of blocks owned by miner ``o`` inside
                            the common prefix of miner ``i``'s and ``j``'s
                            chains. This one tensor replaces every structural
                            chain comparison. Its update rules are closed
                            under the two events of the system (own-append,
                            adopt-published), so the representation is exact —
                            see tests/test_state_equivalence.py which checks
                            it against a literal chain simulator.
  * ``own_cnt[i]``        — own blocks in own chain, ``cp[i,i,i]``.
  * ``own_in[j, o]``      — ``o``'s blocks in ``j``'s chain, ``cp[j,j,o]``:
                            final per-miner stats against the best chain
                            (main.cpp:22-30) are ``own_in[b, i]`` minus
                            ``b``'s unpublished tail when ``i == b``.
  * ``own_cp[i, j]``      — own blocks in the common prefix with ``j``,
                            ``cp[i,j,i]``: reorg stale accounting
                            (simulation.h:124-142) pops
                            ``own_cnt[i] - own_cp[i,b]`` blocks of an
                            adopter ``i``.

**Lazy diagonals — the perf keystone of both modes.** A block find appends
at ``cp[w,w,w]`` = ``own_cp[w,w]`` = ``own_in[w,w]`` — always on a
diagonal. Those diagonals are therefore NOT maintained: ``own_cnt`` (a
length-M vector) is their single authority, finds increment ONLY it, and
every read of a stale diagonal (``own_cp[b,b]``, ``own_in[b,b]``, the
``i == j`` planes of ``cp`` through ``cp[b,b,o]``) corrects the entry
arithmetically from ``own_cnt``/``own_in``. Adoption sweeps rewrite rows
and columns with authoritative values. Net effect: the hot find path
touches O(M) state in fast mode and O(M) in exact mode (previously O(M^3):
the three-way one-hot ``cp`` increment), and the per-sweep M^3 work drops
to one ``cp[b, :, :]`` contraction plus the three-way adoption select.

"Fast" mode drops the 3-index tensor and keeps only ``own_cnt`` /
``own_in`` / ``own_cp``, accepting an approximation in ``own_cp``'s
adoption update (an adopter's rows are reset as if its new chain shared no
history with third parties).

Accuracy contract of fast mode, for honest rosters (property-tested on
adversarial streams in tests/test_property_equivalence.py):

  * every consensus observable is EXACT: ``own_in``/``own_cnt`` (each
    chain's per-owner block counts, hence blocks_found / blocks_share /
    best_height) are maintained exactly — their updates (+1 on own find;
    copy of the winner's row minus its in-flight suffix on adopt) never
    consult ``own_cp``;
  * the ``stale`` counter is an elementwise LOWER BOUND of the true count.
    Every implied ``own_above`` update is an exact nonneg increment, a copy
    of another entry, or a zeroing of the adopter's row — so by induction
    ``own_above <= truth`` elementwise, and stale increments never
    overcount. The shortfall is realized only when an adopter's adopted
    chain contains its own blocks above that chain's fork point with a
    *third* miner that later wins — a compound-race geometry needing two
    overlapping forks, probability ~ (max_prop/interval)^2 per block. At
    the boundary of the auto-routing domain (ratio 1e-2,
    config.FAST_MODE_MAX_RACE_RATIO) the stale-rate error is ~1e-4; at the
    reference's 1 s-propagation default (ratio 1.7e-3) it is ~3e-6.

``mode="auto"`` therefore routes selfish rosters (deep reorgs make the
third-party term first-order) and honest rosters above the ratio threshold to
"exact"; everything else keeps the pairwise representation.

TPU-first numerics: every on-device value is 32-bit. TPUs have no native
64-bit integer or float ALU (XLA emulates both as 32-bit pairs at a large
slowdown), so times are int32 milliseconds *relative to a per-chunk origin*:
the engine re-bases every run's clock to 0 after each fixed-step chunk
(:func:`rebase`), and the host tracks absolute elapsed time in int64 numpy.
Sentinels/caps are sized so no int32 arithmetic here can overflow:
``INF_TIME`` (2^30) > ``TIME_CAP`` (2^29, the farthest a run may advance
within one chunk before freezing until the next re-base) > ``INTERVAL_CAP``
(2^27 ms ~ 1.55 days, a clamp on single interval draws whose exceedance
probability at the 600 s reference mean is e^-223). Cross-miner indexing
(winner, best-chain owner) was historically ALL one-hot arithmetic rather
than gather/scatter; since the miner-axis gather restructuring
(``SimConfig.consensus_gather``, default on) the hot sweep instead carries
the best-chain owner as the scalar index ``_best_chain`` already computes
and reads its rows with ``lax.dynamic_index_in_dim`` — O(M^2) moves instead
of O(M^3) MACs for the ``cp`` plane read — while every *write* stays a dense
masked select. The legacy one-hot reads are retained behind the knob for
A/B timing, bisection, and as the fallback if Mosaic's sublane-axis dynamic
slice lowers poorly on some TPU generation (next-TPU-window checklist).

**Per-chunk count re-basing** (``SimConfig.count_rebase``, default on)
extends the time re-base discipline to the block-count leaves: at every
chunk boundary :func:`rebase_counts` subtracts the per-owner common base
(the min of owner o's count over every stored prefix) from ``cp`` /
``own_*`` / ``height``, the engine accumulates the subtracted bases per run
in its carried aux exactly like elapsed time, and :func:`final_stats`
re-adds them — so stored counts are bounded by one chunk's growth plus a
small divergence residual instead of the whole run's block count, and the
int16 packed layout survives year-long universes (``stale``, the one
monotone accumulator, is excluded and stays int32).

Everything in this module operates on a single unbatched run; the engine vmaps
over runs and lax.scans over events.
"""

from __future__ import annotations

from typing import NamedTuple, Optional

import numpy as np
import jax
import jax.numpy as jnp

from .config import SimConfig
from .sampling import winner_thresholds32

I32 = jnp.int32
#: Time dtype. int32 by design (see module docstring); the name survives from
#: the earlier 64-bit engine so call sites read as "the time dtype".
TIME = jnp.int32
I64 = TIME  # back-compat alias used by tests/testing helpers

#: Packed-state dtypes for the block-COUNT leaves (heights, private/stale
#: counters, group counts, the consensus count tensors): int16 halves their
#: scan-carry/VMEM footprint whenever SimConfig.resolved_count_dtype proves
#: the per-run event bound fits (values are identical — int16 arithmetic is
#: exact in range). Time leaves always stay TIME (int32): clocks span 2^30.
COUNT_DTYPES = {"int32": jnp.int32, "int16": jnp.int16}

#: Sentinel for "no arrival" (empty group slot). Strictly greater than any
#: reachable in-chunk time. The reference uses milliseconds::max for private
#: blocks (simulation.h:20); private blocks here are counted, not stored.
#: np scalars, not jnp: module import must not initialize an XLA backend
#: (jax.distributed.initialize in a worker process forbids it), and np.int32
#: promotes identically inside traced code.
INF_TIME = np.int32(2**30)

#: A run freezes (stops advancing within the current chunk) once its relative
#: clock passes this; the next chunk re-bases it back to 0. Bounds every time
#: value below INF_TIME: t can overshoot the cap by at most one cut-through
#: (INTERVAL_CAP), and arrivals sit at most max-propagation (2^24) above t,
#: so 2^29 + 2^27 + 2*2^24 < 2^30 and nothing int32 here can overflow.
TIME_CAP = np.int32(2**29)

#: Clamp on a single exponential interval draw, in ms.
INTERVAL_CAP = np.int32(2**27)

#: Re-based past tips clamp here; two competing equal-height tips can never
#: both be this old (one block per ~10 min), so the first-seen order among
#: live candidates is preserved.
NEG_TIME_CAP = np.int32(-(2**29))


class SimParams(NamedTuple):
    """Static per-network arrays, closed over by the jitted step."""

    thresholds: jax.Array  # uint32 [M] cumulative winner-draw thresholds
    prop_ms: jax.Array  # int32 [M]
    selfish: jax.Array  # bool [M]
    mean_interval_ms: float
    # uint32 limbs of the reference's cumulative uint64 thresholds, used by
    # the rng="xoroshiro" draw path (bit-exact 64-bit compare on TPU).
    thr64_hi: jax.Array = None
    thr64_lo: jax.Array = None


def make_params(config: SimConfig) -> SimParams:
    from .sampling import winner_thresholds
    from .xoroshiro import thresholds64_limbs

    net = config.network
    pct = np.array([m.hashrate_pct for m in net.miners])
    t64_hi, t64_lo = thresholds64_limbs(winner_thresholds(pct))
    return SimParams(
        thresholds=jnp.asarray(winner_thresholds32(pct)),
        prop_ms=jnp.asarray([m.propagation_ms for m in net.miners], dtype=I32),
        selfish=jnp.asarray([m.selfish for m in net.miners], dtype=jnp.bool_),
        mean_interval_ms=net.block_interval_s * 1e3,
        thr64_hi=jnp.asarray(t64_hi),
        thr64_lo=jnp.asarray(t64_lo),
    )


class SimState(NamedTuple):
    """Per-run simulation state (one element of the vmapped batch)."""

    t: jax.Array  # int32 [] current chunk-relative simulation time (ms)
    next_block_time: jax.Array  # int32 [] relative time of the next block find
    # best_height_prev and n_private exist only for the selfish race/reveal
    # machinery; a fast-mode honest roster carries None instead (an empty
    # pytree leaf, like fast mode's cp) — the Pallas kernel's _FAST_LEAVES
    # never had them, and the scan carry should not round-trip two leaves
    # that are provably always zero.
    best_height_prev: Optional[jax.Array]  # int32 [] best published height after last notify
    height: jax.Array  # int32 [M] own chain length (genesis excluded)
    n_private: Optional[jax.Array]  # int32 [M] trailing private selfish blocks
    stale: jax.Array  # int32 [M] own blocks reorged out (simulation.h:133)
    base_tip_arrival: jax.Array  # int32 [M] arrival of highest arrived block
    group_arrival: jax.Array  # int32 [M, K] in-flight own block groups (sorted)
    group_count: jax.Array  # int32 [M, K]
    overflow: jax.Array  # int32 [] group-slot overflow events (diagnostic)
    cp: Optional[jax.Array]  # int32 [M, M, M] common-prefix owner counts (exact mode;
    #   the i == j planes are stale — own_in / own_cnt are their authority)
    own_cp: jax.Array  # int32 [M, M] own blocks in lca(i, j) = cp[i, j, i] (diag stale)
    own_in: jax.Array  # int32 [M, M] own_in[j, i] = i's blocks in j's chain = cp[j, j, i] (diag stale)
    own_cnt: jax.Array  # int32 [M] own blocks in own chain = cp[i, i, i] (the authority)


def init_state(
    n_miners: int, group_slots: int, exact: bool, count_dtype=I32,
    any_selfish: bool = True, count_rebase: bool = False,
) -> SimState:
    """``count_dtype`` (int32, or int16 when SimConfig.resolved_count_dtype
    packs) types every block-count leaf; every update below derives its
    arithmetic dtype from the leaves, so the carried tree keeps the packed
    layout through the whole chunk (a dtype slip fails loud as a lax.scan
    carry mismatch).

    A fast-mode honest roster (``exact=False, any_selfish=False``) drops the
    selfish-only leaves ``n_private``/``best_height_prev`` to None — both
    are invariantly zero there, and None is an empty pytree leaf, so the
    carry stops paying their HBM round trip (exact mode keeps them even for
    honest rosters: its kernel leaf list is mode-, not roster-, shaped).

    ``count_rebase`` (SimConfig.count_rebase) keeps ``stale`` int32: it is
    the one monotone accumulator :func:`rebase_counts` does NOT re-base (it
    feeds no consensus compare, only final_stats), so under re-basing its
    packed bound would be the full-duration one the other leaves escaped."""
    m, k = n_miners, group_slots
    cdt = count_dtype
    keep_private = exact or any_selfish
    return SimState(
        t=jnp.zeros((), TIME),
        next_block_time=jnp.zeros((), TIME),
        best_height_prev=jnp.zeros((), cdt) if keep_private else None,
        height=jnp.zeros((m,), cdt),
        n_private=jnp.zeros((m,), cdt) if keep_private else None,
        stale=jnp.zeros((m,), I32 if count_rebase else cdt),
        base_tip_arrival=jnp.zeros((m,), TIME),
        group_arrival=jnp.full((m, k), INF_TIME, TIME),
        group_count=jnp.zeros((m, k), cdt),
        overflow=jnp.zeros((), I32),
        cp=jnp.zeros((m, m, m), cdt) if exact else None,
        own_cp=jnp.zeros((m, m), cdt),
        own_in=jnp.zeros((m, m), cdt),
        own_cnt=jnp.zeros((m,), cdt),
    )


def rebase(state: SimState) -> tuple[SimState, jax.Array]:
    """Shift the run's clock origin to ``state.t``; returns (state, elapsed).

    Every stored time moves down by ``t`` (INF slots stay INF, old tips clamp
    at NEG_TIME_CAP); the host adds ``elapsed`` to its int64 absolute clock.
    Called between chunks so int32 times never overflow on year-long runs.
    """
    t = state.t
    return state._replace(
        t=jnp.zeros((), TIME),
        next_block_time=state.next_block_time - t,
        base_tip_arrival=jnp.maximum(state.base_tip_arrival - t, NEG_TIME_CAP),
        # Pending arrivals clamp at NEG_TIME_CAP like base tips. In event
        # stepping they are always > t at re-base (cut-through never passes a
        # pending arrival), so the clamp is a defensive no-op — but it is what
        # guarantees the invariant the notify() do-gate relies on: every
        # stored arrival >= NEG_TIME_CAP.
        group_arrival=jnp.where(
            state.group_arrival >= INF_TIME,
            INF_TIME,
            jnp.maximum(state.group_arrival - t, NEG_TIME_CAP),
        ),
    ), t


def rebase_counts(state: SimState) -> tuple[SimState, jax.Array]:
    """Shift every block-count leaf down by the per-owner common base;
    returns ``(state, base)`` with ``base`` int32 [M] — the count twin of
    :func:`rebase`, called by the engines at each chunk boundary when
    ``SimConfig.count_rebase`` is on. The host/aux accumulates ``base`` per
    run exactly like elapsed time; :func:`final_stats` re-adds it.

    ``base[o]`` is the elementwise min of owner ``o``'s count over every
    stored prefix statistic — by construction no subtraction underflows, and
    every consensus compare is shift-invariant (heights all move by
    ``sum(base)``, owner-o counts all by ``base[o]``; the sweep only ever
    forms differences within one class), so results are bit-identical after
    the final re-add (pinned by tests/test_consensus_gather.py).

    The lazy diagonals (module docstring) are refreshed to their corrected
    values FIRST: a diagonal last written many chunks ago would otherwise
    drift arbitrarily far below the accumulated base. Refreshing is
    output-invisible — every diagonal read already corrects from
    ``own_cnt`` — but it pins the min (and therefore the residual bound) to
    live values. ``stale`` / ``n_private`` / ``group_count`` stay untouched:
    the first is a monotone accumulator outside the consensus algebra (kept
    int32 under re-basing), the latter two are bounded by in-flight work."""
    m = state.height.shape[0]
    cdt = state.height.dtype
    eye = jnp.eye(m, dtype=jnp.bool_)
    own_cnt = state.own_cnt
    own_in = jnp.where(eye, own_cnt[None, :], state.own_in)
    own_cp = jnp.where(eye, own_cnt[None, :], state.own_cp)
    cp = state.cp
    if cp is not None:
        # The i == j planes are the stale diagonals; their corrected value
        # is the (refreshed) own_in row.
        cp = jnp.where(eye[:, :, None], own_in[:, None, :], cp)
        base = jnp.min(cp, axis=(0, 1))  # [o] over every (i, j) prefix
        # own_cp/own_in are derived views of cp in exact mode; folding them
        # into the min anyway keeps the no-underflow guarantee independent
        # of that representation invariant.
        base = jnp.minimum(base, jnp.min(own_cp, axis=1))  # owner = row
    else:
        base = jnp.min(own_cp, axis=1)
    base = jnp.minimum(base, jnp.min(own_in, axis=0))  # owner = column
    base = jnp.minimum(base, own_cnt)
    base_h = jnp.sum(base, dtype=cdt)  # heights shift by the total base
    bhp = state.best_height_prev
    return state._replace(
        best_height_prev=None if bhp is None else bhp - base_h,
        height=state.height - base_h,
        cp=None if cp is None else cp - base[None, None, :],
        own_cp=own_cp - base[:, None],
        own_in=own_in - base[None, :],
        own_cnt=own_cnt - base,
    ), base.astype(I32)


def _at(vec: jax.Array, onehot: jax.Array) -> jax.Array:
    """vec[w] for one-hot w, as arithmetic (no gather); keeps vec's dtype so
    packed count leaves stay packed."""
    return jnp.sum(jnp.where(onehot, vec, 0), dtype=vec.dtype)


def _take_miner(arr: jax.Array, idx: jax.Array, axis: int = 0) -> jax.Array:
    """``arr[..., idx, ...]`` along ``axis`` for a scalar traced miner index:
    the consensus_gather read primitive (one dynamic slice — O(size/M) moves
    — where the one-hot path burned a contract-and-sum over the whole
    array). Keeps dtype; the index is always in range by construction
    (_best_chain always has >= 1 candidate)."""
    return jax.lax.dynamic_index_in_dim(arr, idx, axis=axis, keepdims=False)


def _push_groups(
    arr: jax.Array,
    cnt: jax.Array,
    new_arrival: jax.Array,
    new_count: jax.Array,
    do: jax.Array,
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Append an (arrival, count) group per miner where ``do`` is set.

    Groups stay sorted because every push for a given miner uses a strictly
    later stamp time with the same propagation delay. Equal-arrival pushes
    merge into the last group (the publish-both race of simulation.h:66-69
    produces two blocks with one arrival). A full buffer merges into the last
    slot, keeping counts exact and arrival = the later one; this bounded-memory
    fallback is counted in the returned overflow increment.

    K=2 (the auto slot count in both modes) takes a split-slot
    specialization: the two slots as plain (M,) limbs with dense selects —
    the Pallas kernel's push_groups2, ported to the scan layout after kernel
    ablation attributed ~half the fast step to exactly this one-hot
    machinery. Case-for-case equal to the generic path (same merge /
    overflow-accumulate rules; slots fill left to right so ``c1 > 0``
    implies full), pinned bit-equal by the state-equivalence and
    scan-vs-pallas suites.
    """
    m, k = arr.shape
    if k == 2:
        a0, a1 = arr[:, 0], arr[:, 1]
        c0, c1 = cnt[:, 0], cnt[:, 1]
        e0 = c0 > 0
        e1 = c1 > 0
        last_arr = jnp.where(e1, a1, a0)
        merge = do & e0 & (last_arr == new_arrival)
        overflowed = do & ~merge & e1
        w0 = do & (~e0 | (merge & ~e1))
        w1 = do & e0 & (e1 | ~merge)
        accum = merge | overflowed
        ncnt = new_count.astype(cnt.dtype)
        arr_new = jnp.stack(
            [jnp.where(w0, new_arrival, a0), jnp.where(w1, new_arrival, a1)],
            axis=-1,
        )
        cnt_new = jnp.stack(
            [
                jnp.where(w0, jnp.where(accum, c0 + ncnt, ncnt), c0),
                jnp.where(w1, jnp.where(accum, c1 + ncnt, ncnt), c1),
            ],
            axis=-1,
        )
        return arr_new, cnt_new, jnp.sum(overflowed.astype(I32), dtype=I32)
    kidx = jnp.arange(k)[None, :]
    n = jnp.sum((cnt > 0).astype(I32), axis=-1, dtype=I32)  # [M]
    last_idx = jnp.maximum(n - 1, 0)
    onehot_last = kidx == last_idx[:, None]
    last_arrival = jnp.sum(jnp.where(onehot_last, arr, 0), axis=-1, dtype=I32)
    merge = do & (n > 0) & (last_arrival == new_arrival)
    overflowed = do & ~merge & (n == k)
    write_idx = jnp.where(merge | overflowed, last_idx, jnp.minimum(n, k - 1))
    onehot = (kidx == write_idx[:, None]) & do[:, None]
    arr_new = jnp.where(onehot, new_arrival[:, None], arr)
    accum = (merge | overflowed)[:, None]
    new_count = new_count.astype(cnt.dtype)
    cnt_new = jnp.where(onehot, jnp.where(accum, cnt + new_count[:, None], new_count[:, None]), cnt)
    return arr_new, cnt_new, jnp.sum(overflowed.astype(I32), dtype=I32)


def _flush_groups(
    arr: jax.Array, cnt: jax.Array, base_tip: jax.Array, t: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array]:
    """Move arrived groups (arrival <= t) into the base, compacting the buffer.

    The arrived set is a prefix (groups are sorted), and the new base tip is
    the arrival of the last flushed group — the chain-highest arrived block,
    which is exactly the published-chain tip the first-seen rule compares
    (main.cpp:74-76). Compaction is a K x K one-hot shift, not a gather.

    K=2 takes the split-slot specialization (see :func:`_push_groups`):
    sortedness makes the arrived set {f0, f0&f1}, so the flush-and-compact
    is a handful of dense selects instead of the K x K one-hot shift."""
    m, k = arr.shape
    if k == 2:
        a0, a1 = arr[:, 0], arr[:, 1]
        c0, c1 = cnt[:, 0], cnt[:, 1]
        f0 = a0 <= t
        f1 = a1 <= t
        new_base = jnp.where(f1, a1, jnp.where(f0, a0, base_tip))
        arr_new = jnp.stack(
            [jnp.where(f1, INF_TIME, jnp.where(f0, a1, a0)),
             jnp.where(f0, INF_TIME, a1)],
            axis=-1,
        )
        zero = jnp.zeros((), cnt.dtype)
        cnt_new = jnp.stack(
            [jnp.where(f1, zero, jnp.where(f0, c1, c0)),
             jnp.where(f0, zero, c1)],
            axis=-1,
        )
        return arr_new, cnt_new, new_base
    kidx = jnp.arange(k)
    arrived = arr <= t
    n_f = jnp.sum(arrived.astype(I32), axis=-1, dtype=I32)
    onehot_tip = kidx[None, :] == (n_f - 1)[:, None]
    flushed_tip = jnp.sum(jnp.where(onehot_tip, arr, 0), axis=-1, dtype=I32)
    new_base = jnp.where(n_f > 0, flushed_tip, base_tip)
    # shifted[m, j] = arr[m, j + n_f[m]]; slots past the end become empty.
    sel = kidx[None, None, :] == (kidx[None, :, None] + n_f[:, None, None])  # [M, K_dst, K_src]
    arr_new = jnp.sum(jnp.where(sel, arr[:, None, :], 0), axis=-1, dtype=I32)
    arr_new = jnp.where(jnp.any(sel, axis=-1), arr_new, INF_TIME)
    cnt_new = jnp.sum(jnp.where(sel, cnt[:, None, :], 0), axis=-1, dtype=cnt.dtype)
    return arr_new, cnt_new, new_base


def found_block(
    state: SimState, params: SimParams, w: jax.Array, any_selfish: bool = True,
    gather: bool = True,
) -> SimState:
    """Miner ``w`` finds a block at ``state.t``; ``w == -1`` is an identity
    (no one-hot matches), which is how the engine expresses "no find due this
    step" without a post-hoc select over every state leaf.

    ``any_selfish`` is a *static* flag: when False (honest-only roster) the
    private/race logic is dropped at trace time, not masked at run time.

    Semantics of ``Miner::FoundBlock`` (reference simulation.h:62-76):
      * honest: append an own block arriving at ``t + propagation``;
      * selfish, not in a 1-block race: append a private block;
      * selfish winning a 1-block race (exactly one private block and the best
        published chain matched our length at the last notify): publish the
        private block *and* the new one, both arriving at ``t + propagation``.

    Reachability note: after any notify, the reveal rule guarantees
    ``n_private <= lead``, so ``n_private == 1`` together with
    ``best_height_prev == height`` (lead 0) cannot survive a sweep — the race
    branch never fires dynamically. The reference carries the identical branch
    with the identical invariant (simulation.h:62-76, unit-tested as the 2013
    paper's case b); it is kept and unit-tested here the same way for parity.
    """
    m = state.height.shape[0]
    cdt = state.height.dtype  # the count dtype (int32, or packed int16)
    onehot_w = jnp.arange(m) == w
    if any_selfish:
        is_selfish = jnp.any(onehot_w & params.selfish)
        if gather:
            # w == -1 (no find due) clamps to index 0 inside dynamic_slice;
            # every consumer of these reads is gated on is_selfish, which the
            # unmatched one-hot forces False, so the clamped values are dead
            # — bit-equal to the one-hot path by construction.
            n_private_w = _take_miner(state.n_private, w)
            height_w = _take_miner(state.height, w)
        else:
            n_private_w = _at(state.n_private, onehot_w)
            height_w = _at(state.height, onehot_w)
        is_race = is_selfish & (n_private_w == 1) & (state.best_height_prev == height_w)
        private_append = is_selfish & ~is_race
        push_count = jnp.where(is_race, 2, 1).astype(cdt)
        push_do = onehot_w & ~private_append
        n_private = state.n_private + jnp.where(
            onehot_w,
            jnp.where(private_append, 1, jnp.where(is_race, -1, 0)),
            0,
        ).astype(cdt)
    else:
        push_count = jnp.ones((), cdt)
        push_do = onehot_w
        n_private = state.n_private

    arrival = state.t + params.prop_ms  # [M]
    arr, cnt, over = _push_groups(
        state.group_arrival,
        state.group_count,
        arrival,
        jnp.full((m,), push_count, cdt),
        push_do,
    )
    height = state.height + onehot_w.astype(cdt)

    # The new block is above every lca and inside no common prefix: only the
    # own-count vector moves, in BOTH modes. The new block sits at
    # cp[w, w, w] / own_cp[w, w] / own_in[w, w] — all on the lazily-maintained
    # diagonals whose authority is own_cnt (module docstring) — so a find
    # touches no M^2 or M^3 state at all.
    own_cnt = state.own_cnt + onehot_w.astype(cdt)

    return state._replace(
        height=height,
        n_private=n_private,
        group_arrival=arr,
        group_count=cnt,
        overflow=state.overflow + over,
        own_cnt=own_cnt,
    )


def _best_chain(
    height: jax.Array, n_private: jax.Array, group_count: jax.Array, tip: jax.Array
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array, jax.Array]:
    """Longest published chain with the first-seen tiebreak (main.cpp:68-82).

    Assumes groups hold only unarrived blocks (call after flushing). Returns
    (owner one-hot, owner index, published height per miner, best height,
    best tip arrival). Ties on both height and tip arrival resolve to the
    lowest miner index, matching the reference's scan order with strict
    comparisons. ``n_private`` is None for fast-mode honest rosters
    (invariantly zero). The scalar owner index is what the
    ``consensus_gather`` read path indexes with — always < m, since >= 1
    candidate exists.
    """
    pub_height = height - jnp.sum(group_count, axis=-1, dtype=group_count.dtype)
    if n_private is not None:
        pub_height = pub_height - n_private
    best_h = jnp.max(pub_height)
    cand = pub_height == best_h
    tip_masked = jnp.where(cand, tip, INF_TIME)
    best_tip = jnp.min(tip_masked)
    winners = cand & (tip_masked == best_tip)
    # First true along the miner axis as a min-index select (the kernel's
    # construction — no sequential cumsum in the hot sweep).
    m = pub_height.shape[0]
    midx = jnp.arange(m)
    b_idx = jnp.min(jnp.where(winners, midx, m))
    onehot_b = midx == b_idx
    return onehot_b, b_idx, pub_height, best_h, best_tip


def notify(
    state: SimState,
    params: SimParams,
    do: Optional[jax.Array] = None,
    any_selfish: bool = True,
    gather: bool = True,
) -> SimState:
    """One best-chain recompute + notify-all sweep at ``state.t``.

    Mirrors one iteration tail of the reference event loop (main.cpp:160-171):
    flush arrivals, find the best published chain, let every selfish miner
    selectively reveal (simulation.h:149-174), then let every miner reorg to
    the best chain if strictly longer (simulation.h:124-142). The reference
    iterates miners sequentially against one fixed best-chain span; no miner's
    notify can affect another's within a sweep, so the vectorized simultaneous
    update is equivalent.

    ``do`` (bool scalar, optional) gates the whole sweep: when False every
    state leaf passes through unchanged. The gate is pushed into the flush /
    reveal / adopt masks so the engine's scan step needs no post-hoc select
    over the state tree. ``any_selfish=False`` (static) drops the reveal logic
    at trace time for honest-only rosters. ``gather`` (static,
    SimConfig.consensus_gather) selects the miner-axis read style: dynamic
    indexing on the best-chain owner's scalar index (default) vs. the legacy
    one-hot contract-and-sum — same entries read, bit-identical results.
    """
    m = state.height.shape[0]
    # Every stored arrival is >= NEG_TIME_CAP (pushes stamp t + prop >= 0;
    # re-basing clamps at NEG_TIME_CAP), so flushing "as of a time below
    # NEG_TIME_CAP" is an exact no-op — the do-gate in one where().
    t_flush = state.t if do is None else jnp.where(do, state.t, NEG_TIME_CAP - 1)
    arr, cnt, base_tip = _flush_groups(
        state.group_arrival, state.group_count, state.base_tip_arrival, t_flush
    )
    onehot_b, b_idx, pub_height, best_h, best_tip = _best_chain(
        state.height, state.n_private, cnt, base_tip
    )
    cdt = state.height.dtype  # the count dtype (int32, or packed int16)
    b32 = onehot_b.astype(cdt)

    # --- Selfish reveal (simulation.h:149-174). Runs before reorg; only for
    # miners whose chain is at least as long as the best published one.
    if any_selfish:
        lead = state.height - best_h
        sc = state.n_private
        can_reveal = params.selfish & (lead >= 0) & (sc > lead)
        if do is not None:
            can_reveal &= do
        reveal_n = jnp.where((sc > 1) & (lead == 1), sc, sc - lead)
        arr, cnt, over = _push_groups(arr, cnt, state.t + params.prop_ms, reveal_n, can_reveal)
        n_private = jnp.where(can_reveal, sc - reveal_n, sc)
    else:
        over = I32(0)
        n_private = state.n_private

    # --- Reorg (simulation.h:124-142): adopt the best chain when strictly
    # longer than the *full* local chain (private blocks included).
    adopt = best_h > state.height
    if do is not None:
        adopt &= do

    cp = state.cp
    own_cp, own_in, own_cnt = state.own_cp, state.own_in, state.own_cnt

    # Shared between the modes (diagonal corrections per the module
    # docstring — own_cnt is the authority for every stale diagonal read).
    # The gather path reads b's rows by the scalar index _best_chain already
    # computed (O(M^2) moves for the cp plane); the legacy path contracts
    # against the one-hot (O(M^3) MACs). Same entries, bit-identical.
    if gather:
        unpub_b = _take_miner(state.height, b_idx) - best_h
        cnt_b = _take_miner(own_cnt, b_idx)  # own chain length in blocks of b
        # own_cp[:, b] = cp[i, b, i] with the stored (stale) [b, b] entry
        # corrected: own blocks in the common prefix with b.
        oc_b = _take_miner(own_cp, b_idx, axis=1)
        oc_b = oc_b + b32 * (cnt_b - _take_miner(oc_b, b_idx))
    else:
        unpub_b = _at(state.height, onehot_b) - best_h
        cnt_b = _at(own_cnt, onehot_b)
        oc_b = jnp.sum(own_cp * b32[None, :], axis=-1, dtype=cdt)
        oc_b = oc_b + b32 * (cnt_b - _at(oc_b, onehot_b))
    # Reorg stale accounting (simulation.h:129-135): own blocks above the
    # lca with b are popped on adoption.
    stale = state.stale + jnp.where(adopt, own_cnt - oc_b, 0)
    # own_in[b, :] = cp[b, b, o] with the same diagonal correction, then
    # minus b's unpublished suffix: per-owner composition of the adopted
    # published chain. (Without the subtraction b's pending blocks would be
    # silently forgotten as future stale.)
    if gather:
        row_b = _take_miner(own_in, b_idx, axis=0)
        row_b = row_b + b32 * (cnt_b - _take_miner(row_b, b_idx))
    else:
        row_b = jnp.sum(own_in * b32[:, None], axis=0, dtype=cdt)
        row_b = row_b + b32 * (cnt_b - _at(row_b, onehot_b))
    row_bpub = row_b - unpub_b * b32  # [M] per-owner counts of b_pub

    if cp is not None:
        # cpb[j, o] = cp[b, j, o]. Its j == b row comes from a stale i == j
        # plane of the stored tensor, but no consumer reads it: the
        # onehot_b selects inside y_val/w_val (and yo/wo) overwrite the
        # b-row with row_bpub — derived from own_in, not cpb — wherever a
        # b-indexed value is used, so no correction is needed.
        if gather:
            cpb = _take_miner(cp, b_idx, axis=0)  # [M, M]
            cpb_diag = jnp.diagonal(cpb)  # [i] = cp[b, i, i]
        else:
            cpb = jnp.sum(cp * b32[:, None, None], axis=0, dtype=cdt)  # [M, M]
            cpb_diag = jnp.sum(cpb * jnp.eye(m, dtype=cdt), axis=1, dtype=cdt)  # [i] = cp[b, i, i]

        # Closed-form cp update: every adopter's chain becomes b's published
        # chain. Factored form — the historical 3-level case analysis
        #   cond_pub = (a_i & (a_j | b_j)) | (b_i & a_j) -> row_bpub
        #   cond_bj  = a_i & ~a_j & ~b_j                 -> cpb[j]
        #   cond_bi  = ~a_i & ~b_i & a_j                 -> cpb[i]
        # is entry-for-entry equal (diagonals included; checked case-by-case
        # using a_b = False, i.e. the best owner never adopts) to TWO
        # tensor-rank selects over precomputed row values:
        #   Y[j] = (a_j | b_j) ? b_pub : cpb[j]   (what any adopter's row j
        #                                          becomes)
        #   W[i] = b_i ? b_pub : cpb[i]           (what row i contributes to
        #                                          an adopting column j)
        #   cp[i,j] = a_i ? Y[j] : (a_j ? W[i] : cp[i,j])
        # One fewer select over the (M, M, M) tensor — the single most
        # expensive op of the exact sweep — and two fewer composed masks.
        a_i = adopt[:, None]
        a_j = adopt[None, :]
        ab = adopt | onehot_b
        y_val = jnp.where(ab[:, None], row_bpub[None, :], cpb)  # [M, M]
        w_val = jnp.where(onehot_b[:, None], row_bpub[None, :], cpb)  # [M, M]
        cp = jnp.where(
            a_i[:, :, None],
            y_val[None, :, :],
            jnp.where(a_j[:, :, None], w_val[:, None, :], cp),
        )
        # The o == i slices of the same update keep own_cp exact; same
        # factoring with the sliced values: Y[j, i] = (a_j | b_j) ?
        # row_bpub[i] : cpb[j, i] and W[i, i] = b_i ? row_bpub[i] :
        # cpb_diag[i].
        yo = jnp.where(ab[None, :], row_bpub[:, None], cpb.T)  # [i, j]
        wo = jnp.where(onehot_b, row_bpub, cpb_diag)  # [i]
        own_cp = jnp.where(a_i, yo, jnp.where(a_j, wo[:, None], own_cp))
    else:
        # Fast pairwise approximation. Adopter rows: the chain IS b_pub now
        # — own blocks above any lca become 0, i.e. own_cp[i, :] =
        # own_cnt_new[i] = row_bpub[i]. Columns toward adopters: lca(i,
        # adopted chain) = lca(i, b_pub), whose own count is own_cp[i, b]
        # minus b's unpublished suffix. Both replacement values are
        # row-broadcasts of (M,) vectors selected by a_i alone, so the
        # historical two nested (M, M) selects collapse to ONE select under
        # the combined mask (case-for-case: a_i -> row_bpub[i]; ~a_i & a_j
        # -> col_cp[i]) — one fewer pass over the densest fast-mode array.
        col_cp = oc_b - unpub_b * b32
        own_cp = jnp.where(
            adopt[:, None] | adopt[None, :],
            jnp.where(adopt, row_bpub, col_cp)[:, None],
            own_cp,
        )

    own_in = jnp.where(adopt[:, None], row_bpub[None, :], own_in)
    own_cnt = jnp.where(adopt, row_bpub, own_cnt)

    height = jnp.where(adopt, best_h, state.height)
    if n_private is not None:
        n_private = jnp.where(adopt, 0, n_private)
    arr = jnp.where(adopt[:, None], INF_TIME, arr)
    cnt = jnp.where(adopt[:, None], 0, cnt)
    base_tip = jnp.where(adopt, best_tip, base_tip)
    if state.best_height_prev is None:
        bhp = None
    else:
        bhp = best_h if do is None else jnp.where(do, best_h, state.best_height_prev)

    return state._replace(
        best_height_prev=bhp,
        height=height,
        n_private=n_private,
        stale=stale,
        base_tip_arrival=base_tip,
        group_arrival=arr,
        group_count=cnt,
        overflow=state.overflow + over,
        cp=cp,
        own_cp=own_cp,
        own_in=own_in,
        own_cnt=own_cnt,
    )


def earliest_arrival(state: SimState) -> jax.Array:
    """Earliest pending block arrival strictly after ``state.t``, INF_TIME if
    none (reference main.cpp:99-112 + simulation.h:92-102, whose NextArrival
    only reports arrivals > cur_time)."""
    return jnp.min(jnp.where(state.group_arrival > state.t, state.group_arrival, INF_TIME))


def final_stats(
    state: SimState, t_end: jax.Array, cbase: Optional[jax.Array] = None
) -> dict[str, jax.Array]:
    """Per-miner stats against the best chain at ``t_end`` (main.cpp:13-41,
    185-191): blocks found in the best chain, share of the best chain, and
    stale blocks per found block. ``t_end`` is the simulation end time in the
    run's current (re-based) frame — the same frame as every stored arrival.
    All ratios are per-run; the runner averages ratios across runs exactly like
    the reference (main.cpp:214-216,230-231).

    ``cbase`` (int32 [M], or None when SimConfig.count_rebase is off) is the
    accumulated per-owner count base the chunk-boundary
    :func:`rebase_counts` calls subtracted: this is the re-add boundary —
    the winner selection runs on the re-based (uniformly shifted) values,
    then found counts gain ``cbase`` and the best height ``sum(cbase)``
    BEFORE any ratio is formed, so every output is bit-identical to an
    un-rebased run."""
    m = state.height.shape[0]
    unarrived = jnp.sum(state.group_count * (state.group_arrival > t_end), axis=-1, dtype=I32)
    pub_height = state.height - unarrived
    if state.n_private is not None:
        pub_height = pub_height - state.n_private
    arrived_mask = state.group_arrival <= t_end
    last_arrived = jnp.max(jnp.where(arrived_mask, state.group_arrival, NEG_TIME_CAP), axis=-1)
    tip = jnp.maximum(state.base_tip_arrival, last_arrived)

    best_h = jnp.max(pub_height)
    cand = pub_height == best_h
    tip_masked = jnp.where(cand, tip, INF_TIME)
    winners = cand & (tip_masked == jnp.min(tip_masked))
    onehot_b = winners & (jnp.cumsum(winners.astype(I32)) == 1)
    b32 = onehot_b.astype(I32)

    # own_in[b, :] = cp[b, b, o] in both modes, diagonal corrected from
    # own_cnt (module docstring): the best chain's per-owner composition.
    own_in_b = jnp.sum(state.own_in * b32[:, None], axis=0, dtype=I32)
    own_in_b = own_in_b + b32 * (_at(state.own_cnt, onehot_b) - _at(own_in_b, onehot_b))
    unpub_b = _at(state.height, onehot_b) - best_h
    found = own_in_b - unpub_b.astype(I32) * b32
    best_h32 = best_h.astype(I32)
    if cbase is not None:
        # Count re-base re-add (rebase_counts): found counts are short by
        # each owner's accumulated base, the best height by their total.
        # Re-added in int32 BEFORE the sign tests and ratios below, so
        # fpos/share/stale_rate see the true values.
        found = found + cbase
        best_h32 = best_h32 + jnp.sum(cbase)
    denom = jnp.maximum(best_h32, 1).astype(jnp.float32)
    fpos = found > 0
    share = jnp.where(fpos, found.astype(jnp.float32) / denom, 0.0)
    stale_rate = jnp.where(
        fpos, state.stale.astype(jnp.float32) / jnp.maximum(found, 1).astype(jnp.float32), 0.0
    )
    return {
        # int32 outputs regardless of the packed count dtype: this is the
        # boundary where packing ends — the engine's finalize sums these
        # over the runs axis, which int16 could not survive.
        "blocks_found": found,
        "blocks_share": share,
        "stale_rate": stale_rate,
        "stale_blocks": state.stale.astype(I32),
        "best_height": best_h32,
        "overflow": state.overflow,
    }
