"""``tpusim fleet`` — preemption-tolerant elastic sweep supervisor.

``run_sweep`` is one fragile process: any worker-level death — a preempted
TPU VM, an OOM-killed process, a tunnel wedged inside C land — kills the
whole grid, and the chaos harness (tpusim.chaos) can only drill faults
*inside* that process. This module is the orchestration half of the
ROADMAP's multi-host fleet item, built to be drillable entirely on CPU: a
**jax-free supervisor** that dispatches sweep points to N subprocess workers
(each worker = one ``run_simulation_config`` process with its own
fingerprinted per-point checkpoint) and keeps the queue draining when
workers die.

Robustness discipline, layer by layer:

  * **Leases + heartbeats + wall-clock watchdog.** Each leased point is
    owned by one worker whose liveness is a heartbeat file (a daemon thread
    in the worker beats every ``heartbeat_s`` even while the main thread is
    blocked in a compile or a device dispatch). The supervisor arms a
    per-worker wall-clock deadline — ``lease_s`` since the last observed
    beat — which is ``chaos.fetch_with_deadline``'s discipline generalized
    from one blocking fetch to whole-process liveness: a worker that outlives
    its lease is SIGKILLed and its point requeued.
  * **Requeue with bounded backoff, bit-equal healing.** A worker that dies
    (SIGKILL/preemption), hangs past its deadline, or exits nonzero gets its
    point requeued with bounded exponential backoff (base doubling, capped,
    deterministic jitter from crc32 so drills reproduce); the replacement
    worker resumes from the dead worker's durable checkpoint, and healed
    rows are **bit-equal** to an uninterrupted sweep (the tests/test_chaos.py
    contract, extended across process boundaries — pinned by
    tests/test_fleet.py).
  * **Poison-point quarantine.** A point that kills ``max_point_failures``
    consecutive workers is quarantined LOUD with its name — the grid keeps
    draining the other points and the supervisor exits nonzero, never an
    infinite crash loop.
  * **Crash-tolerant supervisor.** The work log is an append-only JSONL
    ledger written with the same torn-line repair as sweep resume
    (telemetry.append_jsonl_line) and read back tolerantly; ``--resume``
    re-adopts orphaned leases (a lease with no matching done event) and
    skips points whose rows already landed — so the supervisor itself can be
    killed and restarted like any of its workers.
  * **Deterministic drills.** The supervisor has its own chaos seams
    (``fleet.spawn``, ``fleet.heartbeat``), and per-point chaos plans are
    injected into workers via the environment (:data:`WORKER_CHAOS_ENV`,
    armed for attempt 0 only — a replacement worker must run clean, the
    same re-arm rule as sweep ``--resume``), so every failure mode above is
    a deterministic drill: see ``drills/``.

Output rows keep ``run_sweep``'s exact schema and point order (out-of-order
completions are buffered and flushed in point order), so a fleet output
diffs clean against a single-process sweep. Only the NamedSharding SPMD
dispatch of the fleet item rides the next TPU window; everything here runs
today.

A supervisor running with ``--telemetry`` is also a trace root
(tpusim.tracing): each spawn injects ``TPUSIM_TRACE_CONTEXT`` into the
worker's environment, so the supervisor ledger plus every per-worker ledger
under ``STATE_DIR/workers`` form ONE correlatable span tree —
``tpusim trace timeline STATE_DIR`` renders the cross-process critical-path
attribution and the orchestration Perfetto timeline from them.

    python -m tpusim fleet propagation --workers 4 --state-dir fleet/ \\
        --telemetry fleet/fleet.tele.jsonl
    python -m tpusim fleet propagation --workers 4 --state-dir fleet/ --resume
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import logging
import os
import signal
import subprocess
import sys
import threading
import time
import zlib
from pathlib import Path
from typing import Any, Callable, Iterable

from .chaos import ChaosError, ChaosInjector, ChaosPlan, InjectedHang, as_injector
from .config import SimConfig
from .provenance import emit_lineage, lineage_armed, lineage_last
from .telemetry import TelemetryRecorder, append_jsonl_line
from .tracing import TRACE_ENV, TraceContext

logger = logging.getLogger("tpusim")

__all__ = [
    "WORKER_CHAOS_ENV",
    "FleetSupervisor",
    "summarize_fleet_spans",
    "worker_main",
    "main",
]


def summarize_fleet_spans(spans: list[dict]) -> dict[str, Any] | None:
    """Digest a telemetry ledger's ``fleet_*`` spans into the one summary
    dict both dashboards render — THE shared extraction behind the
    ``tpusim report`` fleet panel and ``tpusim watch``'s fleet line, so the
    two surfaces cannot drift apart on the span schema. Returns None when
    the ledger has no fleet spans; tolerates foreign/partial attrs (missing
    keys, non-list leases) like every other ledger consumer."""
    fleet_sp = [sp for sp in spans if str(sp.get("span", "")).startswith("fleet_")]
    if not fleet_sp:
        return None
    by: dict[str, list[dict]] = {}
    for sp in fleet_sp:
        by.setdefault(sp["span"], []).append(sp)
    status = (by["fleet_status"][-1].get("attrs") or {}) if by.get("fleet_status") else {}
    quarantined = status.get("quarantined")
    if not isinstance(quarantined, list):
        quarantined = [
            (sp.get("attrs") or {}).get("target", "?")
            for sp in by.get("fleet_quarantine", ())
        ]
    leases = status.get("leases")
    leases = (
        [entry for entry in leases if isinstance(entry, dict)]
        if isinstance(leases, list) else []
    )
    dones = len(by.get("fleet_done", ()))
    return {
        "status": status,
        "spawns": len(by.get("fleet_spawn", ())),
        "adopts": len(by.get("fleet_adopt", ())),
        "points_done": status.get("points_done", dones),
        "points_total": status.get("points_total"),
        "workers_alive": status.get("workers_alive"),
        "queued": status.get("queued"),
        "requeues": [sp.get("attrs") or {} for sp in by.get("fleet_requeue", ())],
        "quarantined": [str(q) for q in quarantined],
        "leases": leases,
    }

#: Environment variable through which the supervisor injects a chaos plan
#: (JSON text, not a path — self-contained across hosts) into one worker.
WORKER_CHAOS_ENV = "TPUSIM_FLEET_WORKER_CHAOS"


# ---------------------------------------------------------------------------
# Worker side.


class _Heartbeat:
    """The worker's liveness signal: a daemon thread appending one JSON line
    ``{"t", "beats", "runs_done", "runs_total"}`` to the heartbeat file every
    ``interval_s`` — even while the main thread is blocked inside a compile
    or a wedged device dispatch, which is exactly when a progress-callback
    heartbeat would go silent and get a healthy worker killed.

    ``progress`` doubles as the worker-side ``fleet.heartbeat`` chaos seam:
    a ``hang`` fault wedges the worker COMPLETELY (beats stop and the run
    freezes), simulating the preempted-VM/wedged-tunnel failure the
    supervisor's lease watchdog exists for."""

    def __init__(self, path: str | Path, interval_s: float, chaos=None):
        self.path = Path(path)
        self.interval_s = interval_s
        self.chaos = chaos
        self._state = {"runs_done": 0, "runs_total": None}
        self._beats = 0
        self._progress_calls = 0
        self._stop = threading.Event()
        self._wedged = threading.Event()
        self._thread = threading.Thread(
            target=self._loop, daemon=True, name="tpusim-fleet-heartbeat"
        )

    def start(self) -> None:
        self.path.parent.mkdir(parents=True, exist_ok=True)
        self._write()
        self._thread.start()

    def _write(self) -> None:
        row = {"t": round(time.time(), 3), "beats": self._beats, **self._state}
        with self.path.open("a") as fh:
            fh.write(json.dumps(row) + "\n")
        # Single-writer by construction: start() beats once BEFORE the
        # thread exists; afterwards only the beat thread calls _write.
        self._beats += 1  # tpusim-lint: disable=JX015 -- handoff precedes start()

    def _loop(self) -> None:
        while not self._stop.wait(self.interval_s):
            if self._wedged.is_set():
                return
            try:
                self._write()
            except OSError:
                # A transient beat-write failure (brief ENOSPC, an NFS
                # stall) must not permanently silence a healthy worker:
                # retry next interval. Only an outage that outlasts the
                # lease becomes a watchdog kill + requeue — the
                # supervisor's recovery path, never a worker crash.
                continue

    def progress(self, done: int, total: int) -> None:
        """The runner's per-batch progress callback; also the worker-side
        ``fleet.heartbeat`` chaos seam (context: beats = callback ordinal
        starting at 1, runs_done)."""
        self._state.update(runs_done=int(done), runs_total=int(total))
        self._progress_calls += 1
        if self.chaos is not None:
            try:
                self.chaos.fire(
                    "fleet.heartbeat",
                    beats=self._progress_calls, runs_done=int(done),
                )
            except InjectedHang:
                # Simulate a full wedge: stop the beat thread, then freeze
                # this (main) thread forever. Only SIGKILL from the
                # supervisor's watchdog ends this process — by design.
                self._wedged.set()
                while True:
                    time.sleep(3600)

    def stop(self) -> None:
        self._stop.set()


def worker_main(argv: list[str] | None = None) -> int:
    """One fleet worker: run one sweep point via ``run_simulation_config``
    with a per-point checkpoint, beating the heartbeat file throughout, and
    atomically publish the ``run_sweep``-schema result row. Spawned by the
    supervisor as ``python -m tpusim.fleet --worker ...``; a chaos plan in
    :data:`WORKER_CHAOS_ENV` is armed across every runner seam (that is how
    the kill/hang/ENOSPC drills reach the worker)."""
    p = argparse.ArgumentParser(prog="tpusim fleet --worker")
    p.add_argument("--point", default=None)
    p.add_argument("--config", type=Path, default=None)
    p.add_argument(
        "--grid", type=Path, default=None,
        help="packed sub-grid manifest JSON ({'unit', 'points': [{'point', "
        "'config'}]}): run the whole sub-grid as packed device programs "
        "(tpusim.packed) and publish ALL its rows in one result object",
    )
    p.add_argument("--result", required=True, type=Path)
    p.add_argument("--heartbeat", required=True, type=Path)
    p.add_argument("--checkpoint", type=Path, default=None)
    p.add_argument(
        "--checkpoint-dir", type=Path, default=None,
        help="per-point piece checkpoints for a --grid unit (tpusim.packed): "
        "a requeued packed sub-grid resumes mid-pack from these instead of "
        "restarting the whole unit",
    )
    p.add_argument("--heartbeat-s", type=float, default=1.0)
    p.add_argument("--single-device", action="store_true")
    p.add_argument("--telemetry", type=Path, default=None)
    args = p.parse_args(argv)
    if (args.grid is None) == (args.point is None):
        p.error("exactly one of --point/--config or --grid is required")
    if args.point is not None and args.config is None:
        p.error("--point needs --config")

    plan_text = os.environ.get(WORKER_CHAOS_ENV)
    injector = ChaosInjector(ChaosPlan.from_json(plan_text)) if plan_text else None
    hb = _Heartbeat(args.heartbeat, args.heartbeat_s, chaos=injector)
    hb.start()  # first beat BEFORE the jax import: the lease covers startup

    if args.telemetry is not None:
        # The clock-handshake span (tpusim.tracing): emitted BEFORE the jax
        # import so the merger can anchor this process's monotonic clock to
        # the supervisor's spawn span — everything between fleet_spawn and
        # the first real work span is then honestly attributed as spawn cost
        # (interpreter + jax import + engine build). The recorder adopts the
        # supervisor's trace context from TPUSIM_TRACE_CONTEXT by itself.
        hs = TelemetryRecorder(args.telemetry)
        hs.emit(
            "worker_start", pid=os.getpid(),
            point=args.point, grid=str(args.grid) if args.grid else None,
        )
        hs.close()

    t0 = time.monotonic()
    if args.grid is not None:
        # Packed sub-grid worker: one run_sweep(packed=True) over the
        # manifest's points — the whole sub-grid as one (or a few) compiled
        # device dispatches, every row in one atomically-published object.
        # run_sweep owns the telemetry recorder for this path.
        manifest = json.loads(args.grid.read_text())
        points = [
            (entry["point"], SimConfig.from_json(Path(entry["config"]).read_text()))
            for entry in manifest["points"]
        ]
        from .sweep import run_sweep

        rows = run_sweep(
            points, quiet=True, packed=True, chaos=injector,
            telemetry_path=args.telemetry, engine_cache={},
            checkpoint_dir=args.checkpoint_dir,
            progress=hb.progress,
            use_all_devices=not args.single_device,
        )
        payload: dict = {"rows": rows}
    else:
        recorder = TelemetryRecorder(args.telemetry) if args.telemetry else None
        config = SimConfig.from_json(args.config.read_text())

        from .runner import run_simulation_config

        try:
            res = run_simulation_config(
                config,
                use_all_devices=not args.single_device,
                progress=hb.progress,
                checkpoint_path=args.checkpoint,
                telemetry=recorder,
                chaos=injector,
            )
        finally:
            if recorder is not None:
                recorder.close()
        # The exact run_sweep row schema (same key order), so fleet output
        # diffs clean against a single-process sweep of the same grid.
        payload = {
            **res.to_dict(),
            "point": args.point,
            "backend": "tpu",
            "elapsed_s": round(time.monotonic() - t0, 3),
        }
        if lineage_armed():
            # The published row's lineage record, citing the run record the
            # runner just emitted in this process — which itself cites the
            # checkpoint_load when this worker healed a dead one's lease.
            # (Grid workers need no equivalent: their rows flow through
            # sweep.emit_row, which records them.) The supervisor writes
            # this payload verbatim, so the on-disk row re-hashes to the
            # same content address.
            emit_lineage(
                "fleet_row", content=payload,
                parents=(lineage_last("run"),),
                point=args.point, runs=payload.get("runs"), backend="tpu",
            )
    tmp = args.result.with_name(args.result.name + ".tmp")
    tmp.write_text(json.dumps(payload))
    os.replace(tmp, args.result)  # atomic publish: the supervisor never
    hb.stop()                     # reads a half-written row
    return 0


# ---------------------------------------------------------------------------
# Supervisor side.


def _read_tail_json(path: Path, nbytes: int = 4096) -> dict | None:
    """Newest parseable JSON object from the tail of an append-only JSONL
    file (the heartbeat read — cheap even on a long-lived beat file, and a
    line torn by a SIGKILL mid-write never hides the beat before it)."""
    try:
        with path.open("rb") as fh:
            fh.seek(0, 2)
            size = fh.tell()
            fh.seek(max(0, size - nbytes))
            tail = fh.read().decode("utf-8", errors="replace")
    except OSError:
        return None
    for line in reversed(tail.splitlines()):
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict):
            return row
    return None


def _load_events(path: Path) -> list[dict]:
    """Tolerant ledger read-back: skip torn/foreign lines, same policy as
    telemetry.load_spans / the sweep ``--resume`` scanner."""
    events: list[dict] = []
    if not path.exists():
        return events
    for line in path.read_text(errors="replace").splitlines():
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        if isinstance(row, dict) and "event" in row:
            events.append(row)
    return events


@dataclasses.dataclass
class _Worker:
    wid: str
    point: str
    attempt: int
    proc: subprocess.Popen
    hb_path: Path
    row_path: Path
    log_path: Path
    spawned_t: float  # wall clock, the pre-first-beat liveness floor
    last_hb: dict | None = None


class FleetSupervisor:
    """Dispatch ``points`` (the ``run_sweep`` point list) to up to
    ``workers`` subprocess workers; survive theirs — and its own — deaths.

    See the module docstring for the robustness contract. Everything
    injectable for tests: ``worker_cmd`` builds a worker argv from an
    assignment dict (the fake-worker harness), ``sleeper`` replaces the poll
    sleep. ``worker_chaos`` is a :class:`~tpusim.chaos.ChaosPlan` (or
    ``{point_name: plan}`` dict) injected via env into the attempt-0 worker
    of the matching point(s) — ``worker_chaos_point`` restricts a single
    plan to one named point."""

    def __init__(
        self,
        points: Iterable[tuple[str, SimConfig]],
        *,
        workers: int = 2,
        runs_scale: float = 1.0,
        state_dir: str | Path,
        out_path: str | Path | None = None,
        lease_s: float = 120.0,
        heartbeat_s: float = 1.0,
        max_point_failures: int = 3,
        backoff_s: float = 0.5,
        backoff_cap_s: float = 30.0,
        poll_s: float = 0.25,
        status_interval_s: float = 2.0,
        resume: bool = False,
        quiet: bool = False,
        single_device: bool = False,
        telemetry_path: str | Path | None = None,
        packed: bool = False,
        grid_size: int | None = None,
        chaos=None,
        worker_chaos=None,
        worker_chaos_point: str | None = None,
        worker_cmd: Callable[[dict[str, Any]], list[str]] | None = None,
        sleeper: Callable[[float], None] | None = None,
    ):
        self.state_dir = Path(state_dir)
        self.out_path = (
            Path(out_path) if out_path is not None
            else self.state_dir / "rows.jsonl"
        )
        self.ledger_path = self.state_dir / "fleet-ledger.jsonl"
        self.workers = max(1, int(workers))
        self.lease_s = lease_s
        self.heartbeat_s = heartbeat_s
        self.max_point_failures = max(1, int(max_point_failures))
        self.backoff_s = backoff_s
        self.backoff_cap_s = backoff_cap_s
        self.poll_s = poll_s
        self.status_interval_s = status_interval_s
        self.resume = resume
        self.quiet = quiet
        self.single_device = single_device
        #: Packed sub-grid dispatch (tpusim.packed): workers receive WHOLE
        #: sub-grids of shape-agreeing points (one packed device program per
        #: worker) instead of single points. ``grid_size`` caps the points
        #: per sub-grid (default: spread each shape group across the worker
        #: count). Leases/requeues/quarantine then operate at sub-grid
        #: granularity; output rows keep per-point schema and order.
        self.packed = packed
        self.grid_size = grid_size
        self._units: dict[str, list[str]] = {}
        self.chaos = as_injector(chaos)
        if isinstance(worker_chaos, (str, Path)):
            # Load ONCE, loud, at construction: a typo'd plan path deferred
            # to spawn time would read as a transient spawn failure, and the
            # "drill" would silently certify a healing path it never ran.
            from .chaos import load_plan

            worker_chaos = load_plan(worker_chaos)
        self.worker_chaos = worker_chaos
        self.worker_chaos_point = worker_chaos_point
        self.worker_cmd = worker_cmd
        self._sleep = sleeper if sleeper is not None else time.sleep

        self.points: list[tuple[str, SimConfig]] = []
        for name, config in points:
            # Same scaling rule as run_sweep, so rows keep the same identity
            # key (point, runs, backend) and --resume interoperates.
            runs = max(1, int(config.runs * runs_scale))
            self.points.append((name, dataclasses.replace(config, runs=runs)))
        self._order = [name for name, _ in self.points]
        if len(set(self._order)) != len(self._order):
            raise ValueError("fleet points must have unique names")

        self.recorder = (
            TelemetryRecorder(telemetry_path) if telemetry_path is not None else None
        )
        if self.chaos is not None and self.recorder is not None:
            self.chaos.bind_telemetry(self.recorder)
            self.recorder.chaos = self.chaos

        # Mutable run state.
        self.live: list[_Worker] = []
        self.failures: dict[str, int] = {}
        self.quarantined: list[str] = []
        self.requeues = 0
        self._rows: dict[str, dict] = {}
        self._attempts: dict[str, int] = {}
        self._queue: list[str] = []
        self._ready_at: dict[str, float] = {}
        self._seq = 0
        self._flush_idx = 0
        self._flushed: set[str] = set()
        self._done_prior: set[str] = set()
        self._last_status_t = 0.0

    # -- plumbing ----------------------------------------------------------

    def _emit(self, span: str, **attrs: Any) -> None:
        if self.recorder is not None:
            self.recorder.emit(span, **attrs)

    def _log_event(self, event: str, **fields: Any) -> None:
        row = {"event": event, "t": round(time.time(), 3), **fields}
        # fsync'd: the work ledger is evidence (leases, requeues, quarantine
        # verdicts) the audit gate joins against — a SIGKILL'd supervisor
        # must not leave its last decision unrecorded or torn.
        append_jsonl_line(self.ledger_path, json.dumps(row), fsync=True)

    def _say(self, msg: str) -> None:
        if not self.quiet:
            print(msg)

    def _worker_plan(self, point: str, attempt: int) -> ChaosPlan | None:
        """The chaos plan (if any) injected into this worker's environment.
        Attempt 0 only: a replacement worker is a fresh process that would
        re-arm every fault count and die at the same seam forever — the same
        reason sweep recovery resumes WITHOUT the plan."""
        if self.worker_chaos is None or attempt != 0:
            return None
        # Packed sub-grid units spawn under a synthetic "grid-…" name, so
        # point-targeted plans must match against the unit's MEMBERS (a plan
        # aimed at pt-b arms the whole unit that carries pt-b).
        members = self._unit_points(point)
        if isinstance(self.worker_chaos, dict):
            for member in members:
                plan = self.worker_chaos.get(member)
                if plan is not None:
                    return plan
            return None
        if (
            self.worker_chaos_point is not None
            and self.worker_chaos_point not in members
        ):
            return None
        return self.worker_chaos

    def _unit_points(self, unit: str) -> list[str]:
        """The sweep points one work unit covers: the sub-grid members for a
        packed grid unit, the point itself otherwise."""
        return self._units.get(unit, [unit])

    def _assignment(self, point: str, attempt: int, wid: str) -> dict[str, Any]:
        workers_dir = self.state_dir / "workers"
        asg = {
            "point": point,
            "attempt": attempt,
            "worker": wid,
            "config_path": self.state_dir / "points" / f"{point}.json",
            "result_path": workers_dir / f"{wid}.row.json",
            "heartbeat_path": workers_dir / f"{wid}.hb.jsonl",
            "checkpoint_path": self.state_dir / "checkpoints" / f"{point}.npz",
            "log_path": workers_dir / f"{wid}.log",
            "telemetry_path": (
                workers_dir / f"{wid}.tele.jsonl"
                if self.recorder is not None else None
            ),
        }
        if point in self._units:
            # Packed sub-grid unit: the worker receives a manifest naming
            # every member point and its config file (written at startup).
            manifest = self.state_dir / "points" / f"{point}.grid.json"
            manifest.write_text(json.dumps({
                "unit": point,
                "points": [
                    {"point": pt,
                     "config": str(self.state_dir / "points" / f"{pt}.json")}
                    for pt in self._units[point]
                ],
            }))
            asg["grid_manifest"] = manifest
        return asg

    def _default_worker_cmd(self, asg: dict[str, Any]) -> list[str]:
        argv = [
            sys.executable, "-m", "tpusim.fleet", "--worker",
            "--result", str(asg["result_path"]),
            "--heartbeat", str(asg["heartbeat_path"]),
            "--heartbeat-s", str(self.heartbeat_s),
        ]
        if asg.get("grid_manifest") is not None:
            # The shared checkpoint dir (per-point files named by point, the
            # run_sweep convention): a replacement worker for a killed packed
            # unit heals MID-PACK from the piece checkpoints instead of
            # restarting the whole sub-grid.
            argv += [
                "--grid", str(asg["grid_manifest"]),
                "--checkpoint-dir", str(self.state_dir / "checkpoints"),
            ]
        else:
            argv += [
                "--point", asg["point"],
                "--config", str(asg["config_path"]),
                "--checkpoint", str(asg["checkpoint_path"]),
            ]
        if self.single_device:
            argv.append("--single-device")
        if asg["telemetry_path"] is not None:
            argv += ["--telemetry", str(asg["telemetry_path"])]
        return argv

    # -- lifecycle ---------------------------------------------------------

    def _spawn(self, point: str) -> None:
        attempt = self._attempts.get(point, 0)
        self._attempts[point] = attempt + 1
        wid = f"w{self._seq:03d}"
        self._seq += 1
        if self.chaos is not None:
            # The fleet.spawn seam: "transient" = spawn failure (requeued by
            # the caller), "sigkill" = the supervisor itself dies — leaving
            # orphaned leases for the --resume drill.
            self.chaos.fire("fleet.spawn", target=point, worker=wid, attempt=attempt)
        asg = self._assignment(point, attempt, wid)
        env = os.environ.copy()
        # Workers import tpusim by module name; anchor the package parent on
        # PYTHONPATH so the spawn works from any supervisor cwd.
        pkg_parent = str(Path(__file__).resolve().parent.parent)
        env["PYTHONPATH"] = os.pathsep.join(
            [pkg_parent] + ([env["PYTHONPATH"]] if env.get("PYTHONPATH") else [])
        )
        plan = self._worker_plan(point, attempt)
        if plan is not None:
            env[WORKER_CHAOS_ENV] = plan.to_json()
        else:
            env.pop(WORKER_CHAOS_ENV, None)
        if self.recorder is not None:
            # Trace-context propagation (tpusim.tracing): the worker's
            # recorder adopts the supervisor's trace_id AND run_id (so the
            # whole fleet is one correlatable tree — and one run in every
            # run_id-grouping surface, which is why tpusim.report partitions
            # by (run_id, process)); parent_span is the worker id of THIS
            # fleet_spawn span.
            env[TRACE_ENV] = TraceContext(
                trace_id=self.recorder.trace_id, parent_span=wid,
                run_id=self.recorder.run_id,
            ).to_env()
        else:
            # No supervisor ledger -> no span to parent to; a context
            # inherited from an OUTER traced process would correlate workers
            # to a spawn span that does not exist.
            env.pop(TRACE_ENV, None)
        argv = (self.worker_cmd or self._default_worker_cmd)(asg)
        asg["result_path"].unlink(missing_ok=True)
        with asg["log_path"].open("ab") as log:
            proc = subprocess.Popen(
                argv, env=env, stdout=log, stderr=subprocess.STDOUT
            )
        w = _Worker(
            wid=wid, point=point, attempt=attempt, proc=proc,
            hb_path=asg["heartbeat_path"], row_path=asg["result_path"],
            log_path=asg["log_path"], spawned_t=time.time(),
        )
        self.live.append(w)
        self._log_event(
            "lease", point=point, worker=wid, attempt=attempt,
            pid=proc.pid, lease_s=self.lease_s, chaos=plan is not None,
        )
        self._emit(
            "fleet_spawn", target=point, worker=wid, attempt=attempt,
            pid=proc.pid, worker_chaos=plan is not None,
        )
        self._say(f"[fleet] {wid} leased {point} (attempt {attempt})")

    def _requeue(self, point: str, worker: str | None, reason: str) -> None:
        failures = self.failures[point] = self.failures.get(point, 0) + 1
        if failures >= self.max_point_failures:
            # Poison-point semantics: quarantine LOUD with the name, keep
            # draining the rest of the grid, exit nonzero at the end —
            # never an infinite crash loop.
            self.quarantined.append(point)
            self._log_event(
                "quarantine", point=point, failures=failures, reason=reason
            )
            self._emit(
                "fleet_quarantine", target=point, failures=failures, reason=reason
            )
            msg = (
                f"[fleet] QUARANTINED point {point!r} after {failures} "
                f"consecutive worker failures (last: {reason}); its "
                f"checkpoint stays in {self.state_dir / 'checkpoints'} for "
                f"forensics — resume retries it with a fresh failure budget"
            )
            logger.error(msg)
            print(msg, file=sys.stderr)
            return
        # Counted only when the point actually goes back on the queue, so
        # the summary/fleet_status number always equals the ledger's count
        # of "requeue" events (a quarantine is not a requeue).
        self.requeues += 1
        backoff = min(self.backoff_s * 2 ** (failures - 1), self.backoff_cap_s)
        # Deterministic jitter (crc32, not salted hash()): drills reproduce,
        # and a fleet of requeues still desynchronizes.
        jitter = (zlib.crc32(f"{point}:{failures}".encode()) % 1000) / 1000.0
        backoff *= 1.0 + 0.25 * jitter
        self._ready_at[point] = time.time() + backoff
        self._queue.append(point)
        self._log_event(
            "requeue", point=point, worker=worker, reason=reason,
            failures=failures, backoff_s=round(backoff, 3),
        )
        self._emit(
            "fleet_requeue", target=point, worker=worker, reason=reason,
            failures=failures, backoff_s=round(backoff, 3),
        )
        self._say(
            f"[fleet] requeued {point} ({reason}, failure {failures}/"
            f"{self.max_point_failures}, backoff {backoff:.2f}s)"
        )

    def _poll_worker(self, w: _Worker, now: float) -> bool:
        """Advance one live worker; True if it left the live set."""
        rc = w.proc.poll()
        if rc is None:
            expired = False
            hb = _read_tail_json(w.hb_path)
            if self.chaos is not None:
                try:
                    self.chaos.fire(
                        "fleet.heartbeat", target=w.point, worker=w.wid,
                        attempt=w.attempt,
                    )
                except InjectedHang:
                    # Supervisor-side drill: the lease reads as already
                    # expired, without waiting out real wall clock.
                    expired = True
                except ChaosError:
                    hb = None  # an injected failed heartbeat read
            if hb is not None and isinstance(hb.get("t"), (int, float)):
                w.last_hb = hb
            beat_t = (w.last_hb or {}).get("t", 0.0)
            age = now - max(w.spawned_t, float(beat_t))
            if expired or age > self.lease_s:
                # The watchdog: fetch_with_deadline's rule at process scope.
                # SIGKILL, not SIGTERM — a wedged worker is past asking.
                self._say(
                    f"[fleet] {w.wid} lease expired on {w.point} "
                    f"(no heartbeat for {age:.1f}s > {self.lease_s}s); killing"
                )
                try:
                    w.proc.kill()
                    w.proc.wait(timeout=10)
                except (OSError, subprocess.TimeoutExpired):
                    # TimeoutExpired: a D-state worker ignored even SIGKILL
                    # (wedged NFS/tunnel I/O — the exact scenario this
                    # watchdog exists for). Abandon the zombie and requeue;
                    # crashing the supervisor here would take down every
                    # other worker's supervision with it.
                    pass
                self.live.remove(w)
                self._requeue(w.point, w.wid, "lease_expired")
                return True
            return False
        self.live.remove(w)
        if rc == 0:
            try:
                payload = json.loads(w.row_path.read_text())
                if not isinstance(payload, dict):
                    raise ValueError("result row is not an object")
                if w.point in self._units:
                    # Packed grid unit: the payload carries every member
                    # row; a missing member is a worker failure, not a
                    # silently half-done grid.
                    rows = payload.get("rows")
                    if not isinstance(rows, list):
                        raise ValueError("grid result has no rows list")
                    by_point = {
                        r.get("point"): r for r in rows if isinstance(r, dict)
                    }
                    missing = [
                        pt for pt in self._units[w.point] if pt not in by_point
                    ]
                    if missing:
                        raise ValueError(f"grid rows missing points {missing}")
                    rows_out = [by_point[pt] for pt in self._units[w.point]]
                else:
                    rows_out = [payload]
            except (OSError, ValueError) as e:
                # Exit 0 with no publishable row is still a worker failure.
                self._requeue(w.point, w.wid, f"bad_result:{type(e).__name__}")
                return True
            for row in rows_out:
                self._rows[row["point"]] = row
            self.failures.pop(w.point, None)
            done_runs = sum(int(r.get("runs") or 0) for r in rows_out)
            # Sum the member rows: run_grid amortizes a pack's wall time
            # over its points, so the last row alone would understate a
            # sub-grid unit's duration by roughly the member count.
            unit_elapsed = round(
                sum(float(r.get("elapsed_s") or 0.0) for r in rows_out), 3
            )
            self._log_event(
                "done", point=w.point, worker=w.wid, attempt=w.attempt,
                elapsed_s=unit_elapsed, runs=done_runs,
                points=len(rows_out),
            )
            self._emit(
                "fleet_done", target=w.point, worker=w.wid, attempt=w.attempt,
                elapsed_s=unit_elapsed, runs=done_runs,
                points=len(rows_out),
            )
            self._say(f"[fleet] {w.wid} finished {w.point}")
        else:
            self._requeue(w.point, w.wid, f"exit:{rc}")
        return True

    def _reap_orphan(self, ev: dict) -> bool:
        """Kill a dead supervisor's still-running worker before re-leasing
        its point: the supervisor-death drill (fleet.spawn sigkill) kills
        only the supervisor, so an orphan worker may still be computing —
        left alone it would race its replacement on the same checkpoint and
        leak a full jax process. PID-reuse guard: kill ONLY a process whose
        /proc cmdline carries BOTH the fleet-worker marker and THIS point's
        name (a real worker's argv has both: `-m tpusim.fleet ... --point
        <name>`); anything else — unreadable /proc, non-Linux, a recycled
        pid now owned by another fleet's worker or an unrelated process
        whose argv merely mentions the point — is left untouched and reads
        as already-dead."""
        pid = ev.get("pid")
        if not isinstance(pid, int) or pid <= 0:
            return False
        try:
            cmdline = Path(f"/proc/{pid}/cmdline").read_bytes().decode(
                errors="replace"
            )
        except OSError:
            return False
        if "tpusim.fleet" not in cmdline or str(ev.get("point")) not in cmdline:
            return False
        try:
            os.kill(pid, signal.SIGKILL)
        except OSError:
            return False
        return True

    def _flush_rows(self) -> None:
        """Append buffered rows to ``out_path`` in POINT order (quarantined
        and previously-done points are skipped), so a fleet output file is
        line-for-line comparable with ``run_sweep``'s."""
        quarantined = {
            pt for unit in self.quarantined for pt in self._unit_points(unit)
        }
        while self._flush_idx < len(self._order):
            name = self._order[self._flush_idx]
            if name in self._done_prior or name in quarantined:
                self._flush_idx += 1
                continue
            row = self._rows.get(name)
            if row is None:
                return
            if name not in self._flushed:
                append_jsonl_line(self.out_path, json.dumps(row))
                self._flushed.add(name)
            self._flush_idx += 1

    def _emit_status(self, now: float, force: bool = False) -> None:
        if self.recorder is None:
            return
        if not force and now - self._last_status_t < self.status_interval_s:
            return
        self._last_status_t = now
        leases = []
        for w in self.live:
            hb = w.last_hb or {}
            beat_t = hb.get("t", w.spawned_t)
            leases.append({
                "point": w.point, "worker": w.wid, "attempt": w.attempt,
                "age_s": round(now - max(w.spawned_t, float(beat_t)), 2),
                "runs_done": hb.get("runs_done"),
                "runs_total": hb.get("runs_total"),
            })
        self._emit(
            "fleet_status",
            workers=self.workers,
            workers_alive=len(self.live),
            queued=len(self._queue),
            points_total=len(self._order),
            points_done=len(self._rows) + len(self._done_prior),
            requeues=self.requeues,
            quarantined=list(self.quarantined),
            leases=leases,
        )

    # -- the supervisor loop ----------------------------------------------

    def run(self) -> dict[str, Any]:
        t0_wall, t0 = time.time(), time.monotonic()
        for sub in ("points", "checkpoints", "workers"):
            (self.state_dir / sub).mkdir(parents=True, exist_ok=True)

        done_keys: set[tuple[str, int, str]] = set()
        if self.resume and self.out_path.exists():
            for line in self.out_path.read_text(errors="replace").splitlines():
                try:
                    row = json.loads(line)
                    done_keys.add((row["point"], row["runs"], row["backend"]))
                except (json.JSONDecodeError, KeyError, TypeError):
                    continue  # torn/foreign line: not done
        orphans: list[dict] = []
        if self.resume:
            state: dict[str, dict] = {}
            for ev in _load_events(self.ledger_path):
                if ev["event"] in ("lease", "done") and "point" in ev:
                    state[ev["point"]] = ev
            orphans = [ev for ev in state.values() if ev["event"] == "lease"]

        try:
            remaining: list[int] = []
            for i, (name, config) in enumerate(self.points):
                if (name, config.runs, "tpu") in done_keys:
                    self._done_prior.add(name)
                    self._say(f"[fleet] {name} already in {self.out_path}; skipping")
                    continue
                (self.state_dir / "points" / f"{name}.json").write_text(
                    config.to_json()
                )
                remaining.append(i)
            if self.packed:
                # Sub-grid units: shape-agreeing points grouped by the
                # jax-free pack planner, each group chunked so the whole
                # fleet's workers stay busy (or to --grid-size). Unit names
                # are deterministic over their membership (crc32), so a
                # resumed supervisor regenerates the same names for the
                # same remaining set and orphan adoption keeps working.
                from .packed import plan_packs

                rem_points = [self.points[i] for i in remaining]
                packs, sequential = plan_packs(rem_points)
                size = self.grid_size or max(
                    1, -(-len(rem_points) // self.workers)
                )
                for pack in packs:
                    for lo in range(0, len(pack.indices), size):
                        members = [
                            rem_points[j][0]
                            for j in pack.indices[lo:lo + size]
                        ]
                        if len(members) == 1:
                            self._queue.append(members[0])
                            continue
                        crc = zlib.crc32("|".join(members).encode())
                        unit = f"grid-{crc:08x}"
                        self._units[unit] = members
                        self._queue.append(unit)
                for j in sequential:
                    self._queue.append(rem_points[j][0])
            else:
                for i in remaining:
                    self._queue.append(self.points[i][0])
            for ev in orphans:
                if ev["point"] in self._queue:
                    # Orphaned lease from a dead supervisor: the point is
                    # requeued (its checkpoint resumes whatever the orphan
                    # saved) with a fresh failure budget — a resume is an
                    # operator decision, like re-running without --chaos.
                    # A still-running orphan worker is reaped first, or it
                    # would race its replacement on the same checkpoint.
                    reaped = self._reap_orphan(ev)
                    self._log_event(
                        "adopt", point=ev["point"],
                        prior_worker=ev.get("worker"), prior_pid=ev.get("pid"),
                        reaped=reaped,
                    )
                    self._emit(
                        "fleet_adopt", target=ev["point"],
                        prior_worker=ev.get("worker"), reaped=reaped,
                    )
                    self._say(
                        f"[fleet] adopted orphaned lease on {ev['point']} "
                        f"(worker {ev.get('worker')} of a previous supervisor"
                        + (", still running — killed)" if reaped else ")")
                    )
            self._log_event(
                "fleet_start", points=len(self._order),
                queued=len(self._queue), workers=self.workers,
                resume=self.resume, run_id=getattr(self.recorder, "run_id", None),
            )
            self._emit_status(time.time(), force=True)

            while self._queue or self.live:
                now = time.time()
                progressed = False
                while len(self.live) < self.workers:
                    ready = [
                        p for p in self._queue
                        if self._ready_at.get(p, 0.0) <= now
                    ]
                    if not ready:
                        break
                    point = ready[0]
                    self._queue.remove(point)
                    try:
                        self._spawn(point)
                    except (ChaosError, OSError) as e:
                        self._requeue(point, None, f"spawn_failed:{e}")
                    progressed = True
                for w in list(self.live):
                    if self._poll_worker(w, now):
                        progressed = True
                self._flush_rows()
                self._emit_status(now, force=progressed)
                if not progressed:
                    self._sleep(self.poll_s)
            self._flush_rows()

            elapsed = time.monotonic() - t0
            summary = {
                "points_total": len(self._order),
                "points_done": len(self._rows) + len(self._done_prior),
                "quarantined": list(self.quarantined),
                "requeues": self.requeues,
                "workers_spawned": self._seq,
                "elapsed_s": round(elapsed, 3),
                "rows": [
                    self._rows[n] for n in self._order if n in self._rows
                ],
            }
            self._log_event(
                "fleet_finish",
                **{k: v for k, v in summary.items() if k != "rows"},
            )
            self._emit_status(time.time(), force=True)
            # The closing span is named "run" so `tpusim watch` exits when
            # the fleet completes, exactly as it does for a single run.
            self._emit(
                "run", t_start=t0_wall, dur_s=elapsed, fleet=True,
                **{k: v for k, v in summary.items() if k != "rows"},
            )
            return summary
        finally:
            if self.recorder is not None:
                self.recorder.close()


# ---------------------------------------------------------------------------
# CLI.


def main(argv: list[str] | None = None) -> int:
    if argv is None:
        argv = sys.argv[1:]
    if argv and argv[0] == "--worker":
        return worker_main(argv[1:])

    from .sweep import baseline_sweeps

    sweeps = baseline_sweeps()
    p = argparse.ArgumentParser(
        prog="tpusim fleet",
        description="Preemption-tolerant elastic sweep supervisor: dispatch "
        "a baseline grid to N subprocess workers with leases, heartbeats, "
        "a wall-clock watchdog, requeue-with-backoff and poison-point "
        "quarantine. See tpusim.fleet.",
    )
    p.add_argument("sweep", choices=sorted(sweeps), help="which baseline grid")
    p.add_argument("--workers", type=int, default=2)
    p.add_argument("--runs-scale", type=float, default=1.0)
    p.add_argument("--max-points", type=int, default=None)
    p.add_argument(
        "--batch-size", type=int, default=None,
        help="override every point's batch size (sets checkpoint granularity "
        "— statistics are batch-invariant)",
    )
    p.add_argument(
        "--state-dir", type=Path, required=True,
        help="fleet state: work ledger, per-point configs/checkpoints, "
        "per-worker heartbeat/result/log files",
    )
    p.add_argument(
        "--out", type=Path, default=None,
        help="result rows JSONL (default STATE_DIR/rows.jsonl); same schema "
        "and point order as python -m tpusim.sweep",
    )
    p.add_argument(
        "--lease-s", type=float, default=120.0,
        help="wall-clock watchdog: a worker with no heartbeat for this long "
        "is SIGKILLed and its point requeued (default 120)",
    )
    p.add_argument("--heartbeat-s", type=float, default=1.0)
    p.add_argument(
        "--max-point-failures", type=int, default=3,
        help="consecutive worker deaths before a point is quarantined loud",
    )
    p.add_argument("--backoff-s", type=float, default=0.5)
    p.add_argument(
        "--resume", action="store_true",
        help="skip points whose rows already landed in --out and re-adopt "
        "orphaned leases from the work ledger (supervisor crash recovery); "
        "quarantined points retry with a fresh failure budget",
    )
    p.add_argument("--telemetry", type=Path, metavar="JSONL")
    p.add_argument(
        "--chaos", type=Path, metavar="PLAN",
        help="supervisor-side chaos plan (fleet.spawn / fleet.heartbeat "
        "seams)",
    )
    p.add_argument(
        "--worker-chaos", type=Path, metavar="PLAN",
        help="chaos plan injected (via env) into the attempt-0 worker of "
        "each point — the worker-kill drill; replacement workers run clean",
    )
    p.add_argument(
        "--worker-chaos-point", default=None, metavar="NAME",
        help="restrict --worker-chaos to one named point",
    )
    p.add_argument(
        "--packed", action="store_true",
        help="dispatch whole sub-grids per worker as packed device programs "
        "(tpusim.packed) instead of single points; leases and quarantine "
        "operate at sub-grid granularity, and a requeued grid heals "
        "MID-PACK from the shared per-point piece checkpoints "
        "(state-dir/checkpoints, written after every packed dispatch)",
    )
    p.add_argument(
        "--grid-size", type=int, default=None,
        help="max points per packed sub-grid (default: spread each shape "
        "group across --workers)",
    )
    p.add_argument("--single-device", action="store_true")
    p.add_argument("--no-probe", action="store_true")
    p.add_argument("--quiet", action="store_true")
    args = p.parse_args(argv)

    if not args.no_probe:
        # Same pre-flight rule as the sweep CLI: prove the backend from a
        # killable subprocess before committing a fleet to it.
        from .probe import probe_backend

        platform = probe_backend()
        if platform is None:
            print(
                "error: accelerator backend unavailable after probe retries; "
                "re-run later or with --no-probe",
                file=sys.stderr,
            )
            return 2
        if platform != "tpu":
            print(
                f"warning: no TPU visible (platform={platform}); fleet "
                f"workers will run on {platform}",
                file=sys.stderr,
            )

    points = sweeps[args.sweep]()
    if args.max_points is not None:
        points = points[: args.max_points]
    if args.batch_size is not None:
        points = [
            (n, dataclasses.replace(c, batch_size=args.batch_size))
            for n, c in points
        ]

    chaos = None
    if args.chaos is not None:
        from .chaos import load_plan

        chaos = ChaosInjector(load_plan(args.chaos))

    sup = FleetSupervisor(
        points,
        workers=args.workers,
        runs_scale=args.runs_scale,
        state_dir=args.state_dir,
        out_path=args.out,
        lease_s=args.lease_s,
        heartbeat_s=args.heartbeat_s,
        max_point_failures=args.max_point_failures,
        backoff_s=args.backoff_s,
        resume=args.resume,
        quiet=args.quiet,
        single_device=args.single_device,
        telemetry_path=args.telemetry,
        packed=args.packed,
        grid_size=args.grid_size,
        chaos=chaos,
        worker_chaos=args.worker_chaos,
        worker_chaos_point=args.worker_chaos_point,
    )
    summary = sup.run()
    if not args.quiet:
        print(
            f"[fleet] {summary['points_done']}/{summary['points_total']} "
            f"points done, {summary['requeues']} requeue(s), "
            f"{len(summary['quarantined'])} quarantined, "
            f"{summary['workers_spawned']} worker(s) spawned "
            f"in {summary['elapsed_s']}s"
        )
    return 3 if summary["quarantined"] else 0


if __name__ == "__main__":
    sys.exit(main())
