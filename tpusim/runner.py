"""Host-side run orchestration: batching, multi-device sharding, checkpoint,
retry.

This subsystem replaces the reference's thread-pool driver (main.cpp:195-220):
``SIM_RUNS`` std::async futures batched by hardware_concurrency become chunked
jitted batches of vmapped runs (tpusim.engine.Engine), optionally sharded over
a ``jax.sharding.Mesh`` of TPU devices with ``shard_map`` and reduced
on-device with ``psum`` — collectives ride ICI instead of a shared-memory
join. It also supplies the auxiliary behaviors the reference lacks
(SURVEY.md section 5): batch-granular checkpoint/resume for preemptible
sweeps, and batch-level failure retry.
"""

from __future__ import annotations

import dataclasses
import json
import logging
import os
import random
import time
import zipfile
from pathlib import Path
from typing import Callable

import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from .chaos import ChaosInjector, ChaosPermanentError, as_injector
from .config import SimConfig
from .convergence import MomentAccumulator
from .engine import Engine
from .profiling import Profiler
from .provenance import (
    checkpoint_address,
    checkpoint_content,
    emit_lineage,
    lineage_armed,
)
from .stats import SimResults
from .telemetry import CompileLedger, TelemetryRecorder, device_memory_attrs

logger = logging.getLogger("tpusim")

__all__ = [
    "run_simulation_config", "make_run_keys", "make_engine",
    "checkpoint_fingerprint", "CheckpointMismatchError",
]


def make_engine(
    config: SimConfig,
    mesh: Mesh | None = None,
    prefer_pallas: bool | None = None,
    *,
    tile_runs: int | None = None,
    step_block: int | None = None,
    cache: dict | None = None,
    compile_ledger=None,
):
    """Pick the fastest engine for the platform: the Pallas VMEM kernel
    (tpusim.pallas_engine) on TPU — fast mode for honest rosters, exact mode
    including the selfish machinery, batch-sharded over single-controller
    device meshes — and the scan engine otherwise (CPU, multi-controller
    meshes, or a fast-mode-selfish config, which raises inside PallasEngine
    and falls through). The two are draw-for-draw identical; callers that
    hit a runtime failure in the Pallas path can rebuild a scan engine
    pinned to the same chunk_steps and lose nothing.

    ``prefer_pallas=True`` is a *forced* choice: an ineligible config
    (mesh, fast-mode selfish, xoroshiro rng, VMEM-guard refusal) raises its
    ValueError instead of silently downgrading to the scan engine. The
    platform-default auto preference downgrades quietly.

    ``tile_runs``/``step_block`` override the Pallas kernel's measured
    defaults for on-hardware sweeps (ignored by the scan engine).

    ``cache`` (a plain dict the caller owns, e.g. one per sweep) reuses a
    previously built engine whose :meth:`Engine.reuse_key` matches the fresh
    candidate's — same compiled-program identity, so a same-shape grid point
    costs a cheap ``rebind`` instead of a recompile. Construction is always
    performed (it is what resolves chunk_steps/superstep and validates the
    config); only the compiled-program cache is shared. Mesh-bound engines
    participate too — the key carries the mesh's axis/device topology.

    ``compile_ledger`` (tpusim.telemetry.CompileLedger) records each
    ``cache`` lookup as an engine-cache hit/miss — the reuse counters the
    perf-observability ledger pairs with the compile spans. Lookups with no
    ``cache`` are not counted (there is no cache to hit)."""
    forced = prefer_pallas is True
    if prefer_pallas is None:
        prefer_pallas = (
            jax.devices()[0].platform == "tpu" and jax.process_count() == 1
        )
        if not prefer_pallas and (tile_runs is not None or step_block is not None):
            # Same strictness as below: a tuning override that silently
            # measured the scan engine would corrupt the sweep it exists for.
            raise ValueError(
                "tile_runs/step_block tune the pallas kernel, but this "
                "platform auto-routes to the scan engine; pass "
                "prefer_pallas/engine='pallas' explicitly or drop the overrides"
            )
    def from_cache(eng):
        if cache is None:
            return eng
        key = eng.reuse_key()
        cached = cache.get(key)
        if compile_ledger is not None:
            compile_ledger.cache_event(cached is not None, key)
        if cached is not None:
            return cached.rebind(config, key)
        cache[key] = eng
        return eng

    if prefer_pallas:
        from .pallas_engine import PallasEngine

        kw = {}
        if tile_runs is not None:
            kw["tile_runs"] = tile_runs
        if step_block is not None:
            kw["step_block"] = step_block
        try:
            return from_cache(PallasEngine(config, mesh, **kw))
        except ValueError:
            if forced or kw:
                # Explicit kernel-tuning overrides exist to sweep the kernel;
                # silently measuring the scan engine instead would corrupt
                # every such sweep point, so they are as strict as forcing.
                raise
            logger.info("config not eligible for the pallas engine; using scan engine")
    return from_cache(Engine(config, mesh))


def make_run_keys(seed: int, start: int, count: int) -> jax.Array:
    """Deterministic per-run keys from a global run index, independent of
    batching — so a resumed or differently-batched sweep samples identically."""
    base = jax.random.key(seed)
    return jax.vmap(lambda i: jax.random.fold_in(base, i))(jnp.arange(start, start + count))


def _zero_sums(template: dict[str, np.ndarray]) -> dict[str, np.ndarray]:
    return {k: np.zeros_like(v, dtype=np.int64 if v.dtype.kind == "i" else np.float64)
            for k, v in template.items()}


class CheckpointMismatchError(ValueError):
    """A checkpoint written by a *different config* — a real operator error
    that must fail loud (merging statistics across configs is silent data
    corruption), unlike a *corrupt* checkpoint, which is an expected outcome
    of a killed window and restarts the point from zero."""


@dataclasses.dataclass
class _Checkpoint:
    path: Path
    fingerprint: str  # config JSON; a resumed sweep must match it exactly
    chaos: ChaosInjector | None = None

    def _tmp(self) -> Path:
        return self.path.with_suffix(".tmp.npz")

    def load(self) -> tuple[int, dict[str, np.ndarray]] | None:
        tmp = self._tmp()
        if tmp.exists():
            # A crash between the tmp write and the atomic replace used to
            # leave this file orphaned forever. Its contents are unverified
            # (possibly torn mid-write), so it is swept, never adopted.
            logger.warning(
                "removing stale checkpoint temp file %s (crash mid-save?)", tmp
            )
            tmp.unlink(missing_ok=True)
        if self.chaos is not None:
            self.chaos.fire("checkpoint.load", path=str(self.path))
        if not self.path.exists():
            return None
        try:
            with np.load(self.path, allow_pickle=False) as data:
                saved_fp = str(data["__config__"])
                if saved_fp != self.fingerprint:
                    raise CheckpointMismatchError(
                        f"checkpoint {self.path} was written by a different config; "
                        f"refusing to merge statistics across configs"
                    )
                runs_done = int(data["__runs_done__"])
                sums = {k: data[k] for k in data.files if not k.startswith("__")}
        except CheckpointMismatchError:
            raise
        except (zipfile.BadZipFile, ValueError, OSError, EOFError) as e:
            # A window killed mid-write (timeout -k) can leave a truncated
            # npz; np.load surfaces that as BadZipFile/ValueError/EOFError
            # depending on where the cut landed. Restart the point from zero
            # instead of crashing the whole sweep — the same tolerance
            # policy as sweep.py's truncated-JSONL repair. KeyError is NOT
            # tolerated: the zip central directory is written last, so a
            # structurally intact npz missing __config__/__runs_done__ is a
            # foreign file, not a torn one — overwriting it silently would
            # be the data-loss class CheckpointMismatchError exists for.
            logger.warning(
                "checkpoint %s is unreadable (%s: %s); restarting this point "
                "from zero", self.path, type(e).__name__, e,
            )
            return None
        return runs_done, sums

    def save(self, runs_done: int, sums: dict[str, np.ndarray]) -> None:
        tmp = self._tmp()
        if self.chaos is not None:
            self.chaos.fire("checkpoint.save", phase="begin", runs_done=runs_done)
        # fsync before the rename and the directory after it: without both,
        # a host crash shortly after "saving" can leave the *renamed* file
        # empty or the rename itself unjournaled — the checkpoint then reads
        # as corrupt exactly when it is needed (the crash it exists for).
        with open(tmp, "wb") as fh:
            np.savez(fh, __runs_done__=runs_done, __config__=self.fingerprint, **sums)
            fh.flush()
            os.fsync(fh.fileno())
        if self.chaos is not None:
            self.chaos.fire("checkpoint.save", phase="pre_replace", runs_done=runs_done)
        tmp.replace(self.path)
        dir_fd = os.open(self.path.parent, os.O_RDONLY)
        try:
            os.fsync(dir_fd)
        finally:
            os.close(dir_fd)
        if self.chaos is not None:
            self.chaos.fire("checkpoint.save", phase="post_replace", runs_done=runs_done)


def checkpoint_fingerprint(config: SimConfig, chunk_steps: int) -> str:
    """The per-point checkpoint identity: everything that affects per-run
    sampling, nothing that doesn't. Shared by the sequential runner
    (``chunk_steps`` = the engine's resolved budget) and the packed
    dispatcher (``config.resolved_chunk_steps`` — pinned equal by
    tests/test_packed_sweep.py), so packed and sequential checkpoints of
    one point are MUTUALLY resumable.

    `runs` and `batch_size` are excluded so a checkpointed sweep can be
    extended or re-batched without invalidating accumulated statistics.
    Flight recording is observational — it changes no draw and no statistic
    (pinned by tests/test_flight.py) — so it stays out and pre-flight
    checkpoints keep resuming. The superstep width K changes only how many
    events one device loop iteration unrolls — the per-event draw mapping
    (and therefore every statistic) is bit-identical across K. Batched wide
    RNG and the packed-state dtype are pure compile-time knobs (pinned by
    tests/test_rng_batch.py), as are the miner-axis gather reads and
    per-chunk count re-basing (tests/test_consensus_gather.py) — all stay
    out, so checkpoints resume across those knobs and across versions from
    before they existed. The default generator is omitted so checkpoints
    from before the rng field existed (identical threefry draws) still
    resume; non-default generators fingerprint explicitly. mode/group_slots
    /chunk_steps fingerprint their *resolved* values: "auto" routing rules
    may change between versions, and a resumed sweep must never silently
    merge fast-mode (lower-bound stale) sums with exact-mode ones."""
    fp_dict = json.loads(config.to_json())
    fp_dict.pop("runs", None)
    fp_dict.pop("batch_size", None)
    fp_dict.pop("flight_capacity", None)
    fp_dict.pop("superstep", None)
    fp_dict.pop("rng_batch", None)
    fp_dict.pop("state_dtype", None)
    fp_dict.pop("consensus_gather", None)
    fp_dict.pop("count_rebase", None)
    if fp_dict.get("rng") == "threefry":
        fp_dict.pop("rng")
    fp_dict["mode"] = config.resolved_mode
    fp_dict["group_slots"] = config.resolved_group_slots
    fp_dict["chunk_steps"] = chunk_steps
    return json.dumps(fp_dict, sort_keys=True)


def run_simulation_config(
    config: SimConfig,
    *,
    mesh: Mesh | None = None,
    use_all_devices: bool = True,
    progress: Callable[[int, int], None] | None = None,
    checkpoint_path: str | Path | None = None,
    max_retries: int = 2,
    retry_backoff_s: float = 0.5,
    sleeper: Callable[[float], None] | None = None,
    profiler: "Profiler | None" = None,
    telemetry: "TelemetryRecorder | None" = None,
    engine: str = "auto",
    tile_runs: int | None = None,
    step_block: int | None = None,
    engine_cache: dict | None = None,
    chaos=None,
    ci_target_rel: float = 0.01,
    ci_target_stat: str | None = None,
) -> SimResults:
    """Run ``config.runs`` simulations and aggregate their statistics.

    Equivalent of the reference's ``main()`` (main.cpp:195-235) minus printing.
    Runs are processed in batches of ``config.batch_size``; when more than one
    device is visible (and no explicit mesh is given) the runs axis of each
    batch is sharded across all devices — the Pallas kernel included, which
    then runs per device on its local shard. ``engine`` forces the execution
    engine: "pallas" (raises on an ineligible config, falls back to the
    draw-identical scan twin only on a runtime kernel failure), "scan", or
    "auto" (the platform default of :func:`make_engine`).

    ``telemetry`` records the structured span ledger (tpusim.telemetry): one
    ``batch`` span per device batch — completion-to-completion wall time,
    host stall while blocked on the device, retry count, and the device-side
    simulation counters the engines accumulate in their carried aux
    (engine.SimCounters) — plus ``checkpoint_load``/``checkpoint_save``,
    ``retry``/``engine_fallback`` events, and one closing ``run`` span with
    the aggregated totals plus the environment identity (jax version, device
    kind/count, tpusim version — telemetry.environment_attrs), so
    cross-host benchmark ledgers are self-describing. Render with
    ``python -m tpusim report``.

    ``engine_cache`` (see :func:`make_engine`) lets a sweep driver share one
    compiled engine across same-shape grid points. Per-run flight-recorder
    arrays (``SimConfig.flight_capacity > 0``) are dropped here — statistics
    aggregation has no use for event rows; ``tpusim trace``
    (tpusim.flight_export) is the collection path for them.

    Failed batches retry up to ``max_retries`` times with bounded
    exponential backoff from ``retry_backoff_s`` (doubling per attempt,
    capped at 30 s) plus deterministic jitter derived from (seed, start,
    attempt) — reproducible in drills, desynchronized across a fleet.
    ``sleeper`` overrides ``time.sleep`` (tests inject a recorder).

    ``chaos`` — a :class:`tpusim.chaos.ChaosPlan`/``ChaosInjector``/plan-JSON
    path — arms deterministic fault injection at the orchestration seams
    (dispatch, checkpoint I/O, telemetry writes, the pipelined fetch); every
    injected fault lands as a ``chaos`` telemetry span. None (the default)
    leaves every seam a no-op check.

    **Streaming convergence telemetry** — each batch's exact int64 moment
    keys (``stats_*``, tpusim.convergence) are folded into a run-scoped
    :class:`~tpusim.convergence.MomentAccumulator` and, when ``telemetry``
    is set, emitted as one ``stats`` span per batch: running mean / standard
    error / 95 % CI half-width per (statistic, miner), the worst relative
    half-width, and an ETA extrapolation toward ``ci_target_rel`` (default
    1 % relative half-width) at the measured steady run rate — flagged
    ``rate_is_first_batch`` while the only measured batch is the
    compile-contaminated first one, mirroring ``steady_is_first_batch``.
    Render live with ``tpusim watch``; like the ``tele_`` counters, moments
    are session-scoped (a checkpoint resume restarts them) and
    multi-controller meshes emit none. This is the estimator substrate the
    ROADMAP's adaptive-precision driver consumes.

    **Run-until-confident** — ``ci_target_stat`` (one of the
    tpusim.convergence statistics: ``blocks_found``/``blocks_share``/
    ``stale_rate``) arms the adaptive-precision DRIVER on that substrate:
    the batch loop stops as soon as the statistic's worst relative 95 % CI
    half-width (across miners) crosses ``ci_target_rel``, instead of only
    displaying an ETA. The run then reports the statistics of the runs it
    actually executed (``SimResults.runs``), and the closing ``run`` span
    records ``stop_reason`` (``"ci_target"`` or ``"runs_exhausted"``) and
    ``converged`` (whether the target was met — also recorded when the run
    exhausted ``config.runs`` without reaching it). ``config.runs`` remains
    the budget ceiling; None (the default) keeps the fixed-run behavior.
    """
    if engine not in ("auto", "pallas", "scan"):
        raise ValueError(f"unknown engine {engine!r}; use auto, pallas or scan")
    if ci_target_stat is not None:
        from .convergence import STATS

        known = tuple(s for s, _, _ in STATS)
        if ci_target_stat not in known:
            raise ValueError(
                f"unknown ci_target_stat {ci_target_stat!r}; use one of {known}"
            )
        if not (ci_target_rel and ci_target_rel > 0):
            raise ValueError(
                "ci_target_stat needs a positive ci_target_rel to stop at"
            )
        if jax.process_count() > 1:
            # Multi-controller meshes drop the moment leaves (same policy as
            # the flight ring), so the stop condition could never fire —
            # refuse loudly rather than silently burning the full budget.
            raise ValueError(
                "ci_target_stat needs the streaming-moment substrate, which "
                "multi-controller meshes do not emit; run single-controller "
                "or drop the stop target"
            )
    chaos = as_injector(chaos)
    if chaos is not None and telemetry is not None:
        chaos.bind_telemetry(telemetry)
        # Both directions: the injector reports through the recorder, and
        # the recorder's own writes are a chaos seam (telemetry.write).
        telemetry.chaos = chaos
    # Compile observability rides with the span ledger: every XLA backend
    # compile this run provokes lands as a `compile` span (duration, engine
    # identity, dispatch context), and make_engine's cache lookups as
    # engine_cache hit/miss spans. Host-side listener only — the compiled
    # programs are untouched (pinned by tests/test_perf_obs.py).
    compile_ledger = CompileLedger(telemetry).install() if telemetry is not None else None
    try:
        _sleep = sleeper if sleeper is not None else time.sleep
        if mesh is None and use_all_devices and len(jax.devices()) > 1:
            mesh = Mesh(np.array(jax.devices()), ("runs",))

        n_dev = 1 if mesh is None else mesh.devices.size
        batch = min(config.batch_size, config.runs)
        batch -= batch % n_dev or 0
        batch = max(batch, n_dev)

        prefer_pallas = None if engine == "auto" else (engine == "pallas")
        eng = make_engine(
            config, mesh, prefer_pallas=prefer_pallas,
            tile_runs=tile_runs, step_block=step_block, cache=engine_cache,
            compile_ledger=compile_ledger,
        )
        # Always (re)assigned: a cache-shared engine may carry a previous run's
        # injector, and this run's policy — chaos or none — must win.
        eng.chaos = chaos
        if compile_ledger is not None:
            compile_ledger.set_context(
                engine=type(eng).__name__, reuse_key=repr(eng.reuse_key())
            )
        # A trailing remainder that doesn't fill the mesh runs on an unsharded
        # single-device engine rather than silently changing the run count.
        engine_unsharded: Engine | None = None

        ckpt = (
            _Checkpoint(
                Path(checkpoint_path),
                checkpoint_fingerprint(config, eng.chunk_steps),
                chaos=chaos,
            )
            if checkpoint_path else None
        )
        runs_done, sums = 0, None
        # The lineage parent this run's record will cite when it resumed from
        # a durable checkpoint — the address is deterministic over
        # (fingerprint, runs_done), so it resolves the SAVING process's
        # checkpoint record even when that process is long dead.
        ck_parent: str | None = None
        if ckpt is not None:
            t_ld = time.perf_counter()
            loaded = ckpt.load()
            if loaded is not None:
                runs_done, sums = loaded
                logger.info("resuming from checkpoint at %d/%d runs", runs_done, config.runs)
                if lineage_armed():
                    # Load-side attestation: a SIGKILL *inside* ckpt.save can
                    # leave the checkpoint durable but its lineage record
                    # unwritten (the process died between the rename and the
                    # emit). The loader just proved the save durable by
                    # loading it, and checkpoint content is deterministic
                    # over (fingerprint, runs_done) — so re-attest it here,
                    # which resolves the same content address the save-side
                    # record would have. Duplicate attestations of one save
                    # are harmless: audit joins by content address.
                    ck_addr = emit_lineage(
                        "checkpoint",
                        content=checkpoint_content(ckpt.fingerprint, runs_done),
                        config_fingerprint=ckpt.fingerprint,
                        runs_done=runs_done, path=str(ckpt.path),
                        attested="load",
                    )
                    ck_parent = emit_lineage(
                        "checkpoint_load",
                        parents=(ck_addr
                                 or checkpoint_address(ckpt.fingerprint, runs_done),),
                        config_fingerprint=ckpt.fingerprint,
                        runs_done=runs_done, path=str(ckpt.path),
                    )
                if telemetry is not None:
                    # Backdated like the batch spans: a default t_start would
                    # stamp the span's END and place the interval in the
                    # future on the wall axis (the timeline merger rebases on
                    # t_mono either way, but the raw ledger should not lie).
                    dur_ld = time.perf_counter() - t_ld
                    telemetry.emit(
                        "checkpoint_load", t_start=time.time() - dur_ld,
                        dur_s=dur_ld, runs_done=runs_done, path=str(ckpt.path),
                    )

        t0 = time.monotonic()
        compile_s: float | None = None
        last_done = t0
        # Run-level totals of the per-batch device counters (engine.SimCounters
        # reductions), reported on the closing "run" span and mirrored in every
        # "batch" span's attrs.
        tele_run = {"reorg_depth_max": 0, "stale_events": 0, "active_steps": 0,
                    "step_slots": 0, "retries": 0}
        hist_run = {"stale_by_miner": None, "reorg_depth_hist": None}
        # Streaming convergence state: exact moment fold + the post-compile run
        # rate the ETA extrapolation divides by (batch 0 carries the jit compile,
        # so it is excluded — the steady_is_first_batch discipline).
        moments = MomentAccumulator()
        steady_rate = {"runs": 0, "s": 0.0}
        # Adaptive-precision driver state (ci_target_stat): the loop's stop
        # verdict plus the last observed relative half-width, reported as
        # stop_reason/converged on the closing run span.
        stop_reason = "runs_exhausted"
        last_rel: float | None = None

        def finalize_with_retries(fin, this_engine, keys, start: int):
            """Block on an async batch and apply the retry/fallback policy; a
            failed async finalize re-runs the batch synchronously. Returns
            (sums, attempts, engine) — the engine that actually produced the
            result, so after a pallas->scan fallback the batch span attributes
            the throughput to the engine that ran, not the one that failed."""
            nonlocal eng
            attempts = 0
            while True:
                try:
                    if chaos is not None:
                        chaos.fire(
                            "engine.dispatch", start=start, batch=start // batch,
                            attempt=attempts, engine=type(this_engine).__name__,
                        )
                    if fin is not None:
                        out, fin = fin, None  # one shot: retries re-dispatch sync
                        return out(), attempts, this_engine
                    return this_engine.run_batch(keys), attempts, this_engine
                except Exception as e:  # noqa: BLE001 — batch-level retry is the point
                    if isinstance(e, ChaosPermanentError):
                        # An injected permanent fault must fail fast on EVERY
                        # engine: the pallas branch below exists for real Mosaic
                        # lowering ValueErrors, and letting it absorb a drill's
                        # permanent fault would report a recovery the guarantee
                        # matrix forbids.
                        raise
                    if not hasattr(this_engine, "scan_twin") \
                            and isinstance(e, (ValueError, TypeError)):
                        # Deterministic config errors (e.g. the int32 block-count
                        # guard) are not transient: fail fast instead of retrying.
                        # Only for non-Pallas engines — Mosaic lowering gaps often
                        # surface as ValueError and must reach the scan_twin
                        # fallback below (where a config error re-raises instantly:
                        # run_batch validates before any device work).
                        raise
                    if hasattr(this_engine, "scan_twin"):
                        # Pallas kernel failed at compile/run time (e.g. a Mosaic
                        # lowering gap on this TPU generation): permanently fall
                        # back to the scan twin — same resolved chunk_steps, so
                        # the sampling identity (and any checkpoint fingerprint)
                        # is unchanged. Does not consume a retry attempt.
                        logger.exception(
                            "pallas engine failed at run %d; falling back to the scan engine",
                            start,
                        )
                        if telemetry is not None:
                            telemetry.emit("engine_fallback", start=start, error=repr(e)[:200])
                        twin = this_engine.scan_twin()
                        if this_engine is eng:
                            eng = twin
                        this_engine = twin
                        continue
                    attempts += 1
                    exhausted = attempts > max_retries
                    # Bounded exponential backoff with deterministic jitter: an
                    # immediate retry hammers whatever infrastructure just failed
                    # (and a fleet of workers retrying in lockstep hammers it
                    # together). The jitter derives from (seed, start, attempt) —
                    # ints only, so hash() is unsalted — never from wall clock:
                    # drills reproduce exactly.
                    pause = 0.0
                    if not exhausted:
                        rnd = random.Random(hash((config.seed, start, attempts)))
                        pause = min(retry_backoff_s * 2 ** (attempts - 1), 30.0)
                        pause *= 1.0 + 0.25 * rnd.random()
                    if telemetry is not None:
                        telemetry.emit(
                            "retry", start=start, attempt=attempts,
                            error=repr(e)[:200], backoff_s=round(pause, 3),
                        )
                    if exhausted:
                        raise
                    logger.exception(
                        "batch at run %d failed (attempt %d); retrying in %.2fs",
                        start, attempts, pause,
                    )
                    if pause > 0:
                        _sleep(pause)

        # Depth-1 pipelined batch loop: batch b+1 is dispatched (run_batch_async)
        # BEFORE batch b is finalized, so the host-side work of b — the transfer,
        # the float64 reduction, checkpoint write, progress callback and b+1's
        # key construction — overlaps b+1's device compute instead of
        # serializing with it. Statistics are order-identical to the sequential
        # loop: batches still accumulate in dispatch order.
        dispatched = runs_done
        pending = None  # (finalize, keys, this_batch, engine, start_index)
        while runs_done < config.runs or pending is not None:
            nxt = None
            if dispatched < config.runs:
                this_batch = min(batch, config.runs - dispatched)
                if mesh is not None and this_batch % n_dev != 0:
                    if engine_unsharded is None:
                        engine_unsharded = Engine(config, None)
                        engine_unsharded.chaos = chaos
                    this_engine = engine_unsharded
                else:
                    this_engine = eng
                if mesh is not None and jax.process_count() > 1:
                    # Multi-controller: assemble the batch keys shard-by-shard so
                    # they can live on a mesh with non-addressable devices.
                    if config.rng != "threefry":
                        raise NotImplementedError(
                            "rng='xoroshiro' is a single-controller A/B mode; "
                            "multi-process runs use the default threefry sampling"
                        )
                    from .distributed import make_global_keys

                    keys = make_global_keys(config.seed, dispatched, this_batch, mesh)
                else:
                    keys = this_engine.make_keys(dispatched, this_batch)
                if compile_ledger is not None:
                    # Dispatch context for any compile this dispatch provokes
                    # (cold program, remainder-batch engine, pallas fallback).
                    compile_ledger.set_context(
                        dispatch="run_batch_async", start=dispatched,
                        engine=type(this_engine).__name__,
                    )
                try:
                    if chaos is not None:
                        chaos.fire("engine.dispatch_async", start=dispatched)
                    fin = this_engine.run_batch_async(keys)
                except Exception:  # noqa: BLE001 — retried at finalize time
                    logger.exception(
                        "async dispatch at run %d failed; will retry synchronously",
                        dispatched,
                    )
                    fin = None
                nxt = (fin, keys, this_batch, this_engine, dispatched)
                dispatched += this_batch

            if pending is not None:
                fin, keys_p, nb, eng_p, start = pending
                t_stall = time.perf_counter()
                batch_sums, attempts, eng_p = finalize_with_retries(fin, eng_p, keys_p, start)
                # Host time blocked waiting for the device: the pipelined-
                # dispatch stall. Near-zero while the pipeline keeps the device
                # ahead of the host; one batch duration when it does not.
                stall_s = time.perf_counter() - t_stall
                now = time.monotonic()
                if profiler is not None:
                    # Completion-to-completion wall time: overlapped batches must
                    # not double-count the pipelined interval.
                    profiler.record(nb, now - last_done)
                # The device-side counters ride the batch sums but aggregate by
                # max/sum rather than into SimResults: strip them before the
                # stat accumulation (checkpoint schema unchanged) and report
                # them through the telemetry ledger instead.
                tele_b = {k: batch_sums.pop(k) for k in list(batch_sums)
                          if k.startswith("tele_")}
                # Streaming-moment keys (tpusim.convergence): telemetry like the
                # tele_ counters, stripped from the stat/checkpoint path (the
                # checkpoint schema is unchanged; a resume restarts the
                # accumulator) and folded into the run-scoped estimator.
                stats_b = {k: batch_sums.pop(k) for k in list(batch_sums)
                           if k.startswith("stats_")}
                # Flight-recorder rows (if the config enabled recording) are
                # event logs, not statistics: drop them from the sum/checkpoint
                # path — `tpusim trace` is their collection pipeline.
                for k in [k for k in batch_sums if k.startswith("flight_")]:
                    del batch_sums[k]
                if stats_b:
                    moments.add(stats_b)
                if tele_b:
                    step_slots = (
                        int(tele_b["tele_chunks_max"]) * eng_p.chunk_steps * nb
                    )
                    tele_run["reorg_depth_max"] = max(
                        tele_run["reorg_depth_max"], int(tele_b["tele_reorg_depth_max"])
                    )
                    tele_run["stale_events"] += int(tele_b["tele_stale_events_sum"])
                    tele_run["active_steps"] += int(tele_b["tele_active_steps_sum"])
                    tele_run["step_slots"] += step_slots
                    for name in hist_run:
                        # tpusim-lint: disable=JX002 -- tele_b values are host
                        # numpy already (run_batch reduces them before returning);
                        # this is dtype bookkeeping, not a device fetch.
                        v = np.asarray(tele_b[f"tele_{name}_sum"], dtype=np.int64)
                        hist_run[name] = v if hist_run[name] is None else hist_run[name] + v
                tele_run["retries"] += attempts
                if telemetry is not None:
                    dur = now - last_done
                    attrs = dict(
                        start=start, runs=nb, engine=type(eng_p).__name__,
                        stall_s=round(stall_s, 6), retries=attempts,
                    )
                    # Memory observability: the engine's static footprint model
                    # (per-run state bytes; the pallas kernel adds its VMEM
                    # estimate vs. budget) plus the backend's live-buffer
                    # watermark — a host-side registry walk at batch
                    # granularity, never a device sync.
                    attrs.update(eng_p.memory_attrs())
                    attrs.update(device_memory_attrs())
                    if tele_b:
                        attrs.update(
                            reorg_depth_max=int(tele_b["tele_reorg_depth_max"]),
                            stale_events=int(tele_b["tele_stale_events_sum"]),
                            active_steps=int(tele_b["tele_active_steps_sum"]),
                            chunks=int(tele_b["tele_chunks_max"]),
                            step_slots=step_slots,
                            stale_by_miner=tele_b["tele_stale_by_miner_sum"].tolist(),
                            reorg_depth_hist=tele_b["tele_reorg_depth_hist_sum"].tolist(),
                        )
                    telemetry.emit("batch", t_start=time.time() - dur, dur_s=dur, **attrs)
                if compile_s is not None:
                    # Post-compile batches only: batch 0's wall time is jit
                    # compile + execution, and a rate fit through it would put
                    # the ETA off by the compile-to-compute ratio.
                    steady_rate["runs"] += nb
                    steady_rate["s"] += now - last_done
                snap = None
                if stats_b and (telemetry is not None or ci_target_stat is not None):
                    rate_is_first_batch = steady_rate["s"] <= 0.0
                    rate = (
                        steady_rate["runs"] / steady_rate["s"]
                        if not rate_is_first_batch
                        else nb / max(now - last_done, 1e-9)
                    )
                    # One snapshot feeds both consumers: the stats span and
                    # the run-until-confident stop check below — they can
                    # never disagree about the CI state they acted on.
                    snap = moments.snapshot(
                        target_rel_hw=ci_target_rel, rate_runs_per_s=rate
                    )
                if telemetry is not None and snap is not None:
                    telemetry.emit(
                        # runs = the accumulator's session scope (what the CI
                        # numbers derive from); runs_done = the run-level
                        # cumulative INCLUDING a resumed checkpoint's base, so
                        # progress displays stay truthful after a resume.
                        "stats", runs=moments.n, runs_done=runs_done + nb,
                        runs_total=config.runs,
                        duration_ms=config.duration_ms,
                        block_interval_s=config.network.block_interval_s,
                        target_rel_hw=ci_target_rel,
                        rate_runs_per_s=round(rate, 3),
                        rate_is_first_batch=rate_is_first_batch,
                        stats=snap,
                    )
                last_done = now
                if compile_s is None:
                    compile_s = now - t0
                if sums is None:
                    sums = _zero_sums(batch_sums)
                for k in sums:
                    sums[k] = sums[k] + batch_sums[k]
                runs_done += nb
                if ckpt is not None:
                    t_ck = time.perf_counter()
                    ckpt.save(runs_done, sums)
                    if lineage_armed():
                        emit_lineage(
                            "checkpoint",
                            content=checkpoint_content(ckpt.fingerprint, runs_done),
                            config_fingerprint=ckpt.fingerprint,
                            runs_done=runs_done, path=str(ckpt.path),
                        )
                    if telemetry is not None:
                        dur_ck = time.perf_counter() - t_ck
                        telemetry.emit(
                            "checkpoint_save", t_start=time.time() - dur_ck,
                            dur_s=dur_ck, runs_done=runs_done, path=str(ckpt.path),
                        )
                if progress is not None:
                    progress(runs_done, config.runs)
                if ci_target_stat is not None and snap is not None:
                    rel = (snap.get(ci_target_stat) or {}).get("rel_hw_max")
                    if isinstance(rel, (int, float)):
                        last_rel = float(rel)
                        if last_rel <= ci_target_rel:
                            # Run-until-confident: the target statistic's CI
                            # crossed the requested width — stop dispatching
                            # and abandon the in-flight batch (its sums were
                            # never folded, so the reported statistics cover
                            # exactly runs_done runs).
                            stop_reason = "ci_target"
                            break
            pending = nxt
    finally:
        # The listener registration is process-global (no unregister in
        # 0.4.x) — unsubscribe on EVERY exit so a failed run cannot leave
        # a stale subscriber narrating a later run's ledger.
        if compile_ledger is not None:
            compile_ledger.uninstall()

    elapsed = time.monotonic() - t0
    assert sums is not None
    converged = None
    if ci_target_stat is not None:
        # converged is also meaningful when the run EXHAUSTED its budget: the
        # closing span then says whether the target happened to be met anyway.
        converged = stop_reason == "ci_target" or (
            last_rel is not None and last_rel <= ci_target_rel
        )
    if telemetry is not None:
        from .telemetry import environment_attrs

        occupancy = (
            tele_run["active_steps"] / tele_run["step_slots"]
            if tele_run["step_slots"] else None
        )
        hists = {k: v.tolist() for k, v in hist_run.items() if v is not None}
        # Compile/cache totals from the session ledger: how many XLA
        # compiles this run actually paid for, and how the engine cache
        # spent vs. saved them — next to compile_s (batch-0 wall time),
        # which also contains trace/lowering the monitoring events omit.
        ledger_attrs = (
            compile_ledger.summary_attrs() if compile_ledger is not None else {}
        )
        telemetry.emit(
            "run", t_start=time.time() - elapsed, dur_s=elapsed,
            runs=runs_done, duration_ms=config.duration_ms,
            block_interval_s=config.network.block_interval_s,
            batch_size=batch, mode=config.resolved_mode,
            engine=type(eng).__name__, compile_s=round(compile_s or 0.0, 4),
            stop_reason=stop_reason, converged=converged,
            ci_target_stat=ci_target_stat,
            occupancy=occupancy, **tele_run, **hists, **ledger_attrs,
            # Environment identity: cross-host ledgers must be
            # self-describing (the ROADMAP's drift note, now machine-read).
            **environment_attrs(),
        )
    res = SimResults.from_sums(
        sums, config, mode=config.resolved_mode, elapsed_s=elapsed, compile_s=compile_s
    )
    if lineage_armed():
        emit_lineage(
            "run", content=res.to_dict(), parents=(ck_parent,),
            config_fingerprint=(
                ckpt.fingerprint if ckpt is not None
                else checkpoint_fingerprint(config, eng.chunk_steps)
            ),
            seed=config.seed, runs=runs_done,
            reuse_key=repr(eng.reuse_key()), backend="tpu",
            run_id=telemetry.run_id if telemetry is not None else None,
        )
    return res
