"""Fleet-wide distributed tracing: cross-process span correlation, clock
rebasing, critical-path wall-clock attribution and the orchestration
Perfetto timeline (``tpusim trace timeline``).

The observability stack instruments a *single process* well (telemetry spans,
compile ledger, convergence stats), but the throughput architecture — the
fleet supervisor (tpusim.fleet), packed sub-grid workers (tpusim.packed) and
the future serving daemon — is a *multi-process* system whose per-worker
JSONL ledgers under ``STATE_DIR/workers`` are mutually uncorrelated. This
module is the correlation layer, jax-free by design (like fleet and watch —
it must run on a host with no backend at all):

  * **Trace-context propagation.** The supervisor's recorder doubles as the
    trace root (``trace_id`` defaults to its ``run_id``); each spawn injects
    :data:`TRACE_ENV` (``TPUSIM_TRACE_CONTEXT``, JSON ``{"trace_id",
    "parent_span", "run_id"}``) into the worker's environment, where
    ``parent_span`` is the worker id of the supervisor's ``fleet_spawn``
    span. :class:`tpusim.telemetry.TelemetryRecorder` adopts the context
    automatically and stamps ``trace_id``/``parent_span``/``process`` (plus
    a monotonic ``t_mono`` and a ``schema`` version) on every span — purely
    schema-additive, old ledgers still load. One fleet run therefore becomes
    ONE correlatable span tree across the supervisor ledger, N worker
    ledgers and each worker's engine/runner spans.
  * **Clock rebasing.** Wall clocks disagree across hosts and can step
    mid-run; monotonic clocks cannot. Every span records ``t_mono`` next to
    ``t_start``, and :func:`assemble` rebases each process onto the
    supervisor's clock through ONE offset derived from the spawn/handshake
    pair (the worker's ``worker_start`` span may never rebase before its own
    ``fleet_spawn``) — so a stepped system clock can neither reorder a
    worker's timeline nor produce a negative duration.
  * **Critical-path attribution.** :func:`attribution` walks the fleet
    window backward, at each instant following the longest-running covering
    interval — the classic critical-path construction — and lands every
    second of the supervisor-measured wall-clock in exactly one category:
    ``spawn`` (process start + imports + engine build), ``compile`` (XLA
    compile spans), ``dispatch`` (device batch compute), ``host_stall``
    (the pipelined fetch stall), ``checkpoint`` (save/load + fsync),
    ``backoff`` (requeue backoff windows), ``supervisor_idle`` (no worker
    alive) — with the remainder reported explicitly as ``unattributed``,
    never silently absorbed.
  * **Orchestration Perfetto export.** :func:`perfetto_timeline` renders one
    process per worker (lease track + work/host slice tracks) plus a
    supervisor track with requeue-backoff slices and instants for chaos
    faults, quarantines and adoptions — loadable in ui.perfetto.dev next to
    the PR-4 device-event traces, gated by the same :func:`validate_perfetto`
    schema check.

CLI::

    python -m tpusim trace timeline STATE_DIR [LEDGER.jsonl ...] \
        [--out orchestration.trace.json] [--format text|md]

``STATE_DIR`` is scanned recursively for ``*.jsonl`` telemetry ledgers
(foreign JSONL files — the fleet work ledger, heartbeat files, sweep rows —
parse as zero spans and are skipped); extra ledger paths merge in. The
newest trace (by span wall time) is assembled unless ``--trace-id`` pins
one.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import os
import sys
from collections import defaultdict
from pathlib import Path
from typing import Any, Iterable

from .telemetry import load_spans

__all__ = [
    "TRACE_ENV",
    "TraceContext",
    "collect_spans",
    "assemble",
    "attribution",
    "worker_utilization",
    "perfetto_timeline",
    "render_timeline",
    "validate_perfetto",
    "timeline_main",
]

#: Environment variable carrying the trace context into spawned workers
#: (JSON text, not a path — self-contained across hosts, like the chaos env).
TRACE_ENV = "TPUSIM_TRACE_CONTEXT"

#: Attribution categories in render order. ``unattributed`` is the explicit
#: remainder — the honesty line the acceptance gate checks, never a bucket
#: anything is deliberately filed under.
CATEGORIES = (
    "spawn", "compile", "dispatch", "host_stall", "checkpoint",
    "backoff", "supervisor_idle", "unattributed",
)

#: Spans that are containers/markers, not work: their intervals would cover
#: the work they merely narrate and must not enter the attribution set.
_NON_WORK_SPANS = frozenset({
    "run", "worker_start", "stats", "fleet_status", "fleet_spawn",
    "fleet_done", "fleet_requeue", "fleet_quarantine", "fleet_adopt",
    "engine_cache", "chaos", "trace", "retry", "engine_fallback",
})


@dataclasses.dataclass(frozen=True)
class TraceContext:
    """The cross-process correlation triple a supervisor hands each worker."""

    trace_id: str
    parent_span: str | None = None
    run_id: str | None = None

    @staticmethod
    def from_env(environ: dict | None = None) -> "TraceContext | None":
        """Parse :data:`TRACE_ENV` tolerantly: a malformed value means no
        context (a worker must never die over its tracing), not an error."""
        raw = (os.environ if environ is None else environ).get(TRACE_ENV)
        if not raw:
            return None
        try:
            row = json.loads(raw)
        except (json.JSONDecodeError, TypeError):
            return None
        if not isinstance(row, dict) or not isinstance(row.get("trace_id"), str):
            return None
        parent = row.get("parent_span")
        run_id = row.get("run_id")
        return TraceContext(
            trace_id=row["trace_id"],
            parent_span=parent if isinstance(parent, str) else None,
            run_id=run_id if isinstance(run_id, str) else None,
        )

    def to_env(self) -> str:
        row: dict[str, str] = {"trace_id": self.trace_id}
        if self.parent_span is not None:
            row["parent_span"] = self.parent_span
        if self.run_id is not None:
            row["run_id"] = self.run_id
        return json.dumps(row)


# ---------------------------------------------------------------------------
# Ledger collection.


def collect_spans(sources: Iterable[str | Path]) -> list[dict]:
    """Load every telemetry ledger under the given files/directories
    (directories are scanned recursively for ``*.jsonl``), tolerantly —
    torn lines and foreign JSONL files (fleet work ledgers, heartbeat files,
    sweep row files: no ``span`` key) contribute zero spans instead of
    errors — and deduplicate EXACT duplicate rows: a supervisor ledger that
    lives inside the state dir AND is passed explicitly (or was copied in by
    an artifact harvest) must not double-count its spans in any panel."""
    files: list[Path] = []
    for src in sources:
        p = Path(src)
        if p.is_dir():
            files.extend(sorted(p.rglob("*.jsonl")))
        elif p.exists():
            files.append(p)
    seen: set[str] = set()
    spans: list[dict] = []
    for f in dict.fromkeys(files):  # a path listed twice loads once
        for sp in load_spans(f):
            key = json.dumps(sp, sort_keys=True)
            if key in seen:
                continue
            seen.add(key)
            spans.append(sp)
    return spans


# ---------------------------------------------------------------------------
# Span-tree assembly + clock rebasing.


@dataclasses.dataclass
class Interval:
    """One categorized stretch of rebased supervisor-clock time."""

    start: float
    end: float
    category: str
    process: str
    span: str
    worker: str | None = None


@dataclasses.dataclass
class WorkerNode:
    wid: str
    point: str
    attempt: int
    spawn_t: float
    end_t: float
    end_reason: str  # "done" | "requeue" | "open"
    process: str | None = None


@dataclasses.dataclass
class FleetTrace:
    """The assembled cross-process trace: rebased spans, the worker tree and
    the categorized interval set every downstream surface derives from."""

    trace_id: str
    run_id: str | None
    t0: float
    t1: float
    spans: list[dict]                 # rebased: each carries _t0/_t1
    workers: dict[str, WorkerNode]    # wid -> node (one per spawn attempt)
    processes: dict[str, dict]        # process -> {"offset","skew_s","worker"}
    intervals: list[Interval]
    instants: list[dict]              # chaos / quarantine / adopt markers
    #: Memoized critical_path() result — the walk is pure in the trace, and
    #: one render touches it several times (attribution, the segment table,
    #: the Perfetto export's embedded summary).
    _segments: "list[Segment] | None" = dataclasses.field(
        default=None, repr=False, compare=False
    )


def _span_times(sp: dict, offset: float | None) -> tuple[float, float]:
    """(start, end) on the rebased axis. ``t_mono`` is recorded at WRITE
    time — the end of a timed span, the instant of an instantaneous one —
    so ``end = offset + t_mono`` and ``start = end - dur_s`` hold for both
    emission styles (backdated ``t_start`` included). Versionless spans
    (no ``t_mono``) fall back to their raw wall times, tolerated but not
    rebased."""
    dur = float(sp.get("dur_s", 0.0) or 0.0)
    t_mono = sp.get("t_mono")
    if offset is not None and isinstance(t_mono, (int, float)):
        end = offset + float(t_mono)
        return end - dur, end
    t = float(sp.get("t_start", 0.0) or 0.0)
    return t, t + dur


def _process_offset(proc_spans: list[dict]) -> float | None:
    """The wall-minus-monotonic offset of one process, from its earliest
    ``t_mono``-bearing span. One offset per process: a wall-clock step
    mid-run changes ``t_start - t_mono`` but never this anchor, so ordering
    and durations inside the process stay monotonic-true."""
    anchored = [
        sp for sp in proc_spans if isinstance(sp.get("t_mono"), (int, float))
    ]
    if not anchored:
        return None
    first = min(anchored, key=lambda sp: float(sp["t_mono"]))
    dur = float(first.get("dur_s", 0.0) or 0.0)
    return (float(first.get("t_start", 0.0)) + dur) - float(first["t_mono"])


def _subtract(start: float, end: float, holes: list[tuple[float, float]]):
    """Yield the pieces of [start, end] not covered by ``holes``."""
    cur = start
    for h0, h1 in sorted(holes):
        if h1 <= cur or h0 >= end:
            continue
        if h0 > cur:
            yield cur, min(h0, end)
        cur = max(cur, h1)
        if cur >= end:
            return
    if cur < end:
        yield cur, end


def assemble(spans: list[dict], trace_id: str | None = None) -> FleetTrace | None:
    """Build the cross-process trace for one fleet run. Returns None when the
    spans carry no ``fleet_spawn`` (nothing to correlate). ``trace_id`` pins
    a trace; the default picks the NEWEST one that has spawn spans (a state
    dir accumulates traces across resumed supervisors).

    Tolerant by the ledger consumers' shared contract: foreign/partial spans
    (missing attrs, no ``t_mono``, unknown names) degrade to whatever can be
    placed, never to an exception."""
    by_trace: dict[str, list[dict]] = defaultdict(list)
    for sp in spans:
        tid = sp.get("trace_id") or sp.get("run_id") or "?"
        by_trace[str(tid)].append(sp)
    if trace_id is None:
        candidates = [
            (max(float(s.get("t_start", 0.0) or 0.0) for s in group), tid)
            for tid, group in by_trace.items()
            if any(s.get("span") == "fleet_spawn" for s in group)
        ]
        if not candidates:
            return None
        trace_id = max(candidates)[1]
    mine = by_trace.get(trace_id, [])
    spawns = [sp for sp in mine if sp.get("span") == "fleet_spawn"]
    if not spawns:
        return None

    by_proc: dict[str, list[dict]] = defaultdict(list)
    for sp in mine:
        by_proc[str(sp.get("process") or "")].append(sp)
    sup_proc = str(spawns[0].get("process") or "")
    processes: dict[str, dict] = {}
    offsets: dict[str, float | None] = {
        proc: _process_offset(group) for proc, group in by_proc.items()
    }

    # Rebase the supervisor first: every other process anchors against it.
    def rebase(sp: dict) -> dict:
        proc = str(sp.get("process") or "")
        t0, t1 = _span_times(sp, offsets.get(proc))
        out = dict(sp)
        out["_t0"], out["_t1"] = t0, t1
        return out

    sup_spans = [rebase(sp) for sp in by_proc.get(sup_proc, [])]
    spawn_t: dict[str, float] = {}
    workers: dict[str, WorkerNode] = {}
    for sp in sup_spans:
        if sp.get("span") != "fleet_spawn":
            continue
        attrs = sp.get("attrs") or {}
        wid = str(attrs.get("worker", "?"))
        spawn_t[wid] = sp["_t1"]
        workers[wid] = WorkerNode(
            wid=wid, point=str(attrs.get("target", "?")),
            attempt=int(attrs.get("attempt", 0) or 0),
            spawn_t=sp["_t1"], end_t=sp["_t1"], end_reason="open",
        )
    for sp in sup_spans:
        attrs = sp.get("attrs") or {}
        wid = str(attrs.get("worker", ""))
        if wid in workers and sp.get("span") in ("fleet_done", "fleet_requeue"):
            workers[wid].end_t = max(workers[wid].end_t, sp["_t1"])
            workers[wid].end_reason = (
                "done" if sp["span"] == "fleet_done" else "requeue"
            )
    processes[sup_proc] = {"offset": offsets.get(sup_proc), "skew_s": 0.0,
                           "worker": None}

    # Worker processes: anchor each on the spawn/worker_start handshake —
    # the rebased worker_start may never precede its own fleet_spawn, so a
    # worker whose wall clock runs BEHIND is shifted forward onto the
    # supervisor axis (the skew is recorded); a clock running ahead is
    # indistinguishable from a slow spawn and left as observed.
    rebased: list[dict] = list(sup_spans)
    for proc, group in by_proc.items():
        if proc == sup_proc:
            continue
        parents = {
            str(sp.get("parent_span"))
            for sp in group if sp.get("parent_span") is not None
        }
        wid = next((p for p in parents if p in workers), None)
        skew = 0.0
        offset = offsets.get(proc)
        if offset is not None and wid is not None:
            anchor = next(
                (sp for sp in group if sp.get("span") == "worker_start"), None
            ) or min(
                (sp for sp in group
                 if isinstance(sp.get("t_mono"), (int, float))),
                key=lambda sp: float(sp["t_mono"]),
            )
            naive = offset + float(anchor["t_mono"])
            floor = spawn_t.get(wid, naive)
            if naive < floor:
                skew = floor - naive
                offset += skew
        offsets[proc] = offset
        processes[proc] = {"offset": offset, "skew_s": skew, "worker": wid}
        if wid is not None and workers[wid].process is None:
            workers[wid].process = proc
        rebased.extend(rebase(sp) for sp in group)

    # A worker whose supervisor never logged its exit (torn ledger, still
    # running) ends at its own last span.
    for node in workers.values():
        if node.end_reason == "open" and node.process is not None:
            ends = [sp["_t1"] for sp in rebased
                    if str(sp.get("process") or "") == node.process]
            if ends:
                node.end_t = max(node.end_t, max(ends))

    run_sp = next(
        (sp for sp in sup_spans
         if sp.get("span") == "run" and (sp.get("attrs") or {}).get("fleet")),
        None,
    )
    if run_sp is not None:
        t0, t1 = run_sp["_t0"], run_sp["_t1"]
    else:
        t0 = min(sp["_t0"] for sp in rebased)
        t1 = max(sp["_t1"] for sp in rebased)
    for node in workers.values():
        if node.end_reason == "open":
            # Still leased when the ledger was read (a live `tpusim watch`
            # frame, a torn supervisor ledger): alive up to the window end.
            node.end_t = max(node.end_t, t1)

    intervals, instants = _build_intervals(rebased, processes, workers)
    run_ids = {sp.get("run_id") for sp in mine if sp.get("run_id")}
    return FleetTrace(
        trace_id=trace_id,
        run_id=sorted(str(r) for r in run_ids)[0] if run_ids else None,
        t0=t0, t1=t1, spans=rebased, workers=workers, processes=processes,
        intervals=intervals, instants=instants,
    )


def _build_intervals(
    rebased: list[dict],
    processes: dict[str, dict],
    workers: dict[str, WorkerNode],
) -> tuple[list[Interval], list[dict]]:
    intervals: list[Interval] = []
    instants: list[dict] = []
    by_proc: dict[str, list[dict]] = defaultdict(list)
    for sp in rebased:
        by_proc[str(sp.get("process") or "")].append(sp)

    for proc, group in by_proc.items():
        wid = processes.get(proc, {}).get("worker")
        names = {sp.get("span") for sp in group}

        def add(start: float, end: float, category: str, span: str) -> None:
            if end > start:
                intervals.append(Interval(start, end, category, proc, span, wid))

        # Specific host-side work carved OUT of the broad batch intervals
        # below (batch spans are completion-to-completion, so consecutive
        # batches tile the loop and would otherwise swallow the compile and
        # checkpoint time the attribution exists to expose).
        holes: list[tuple[float, float]] = []
        for sp in group:
            name = sp.get("span")
            if name == "compile":
                add(sp["_t0"], sp["_t1"], "compile", name)
                holes.append((sp["_t0"], sp["_t1"]))
            elif name in ("checkpoint_save", "checkpoint_load"):
                add(sp["_t0"], sp["_t1"], "checkpoint", name)
                holes.append((sp["_t0"], sp["_t1"]))

        broad = [sp for sp in group if sp.get("span") in ("batch", "packed_dispatch")]
        if not broad:
            # The packed path's sweep_point spans only matter when no finer
            # dispatch record exists (a foreign or minimal ledger).
            broad = [sp for sp in group if sp.get("span") == "sweep_point"]
        for sp in broad:
            stall = float((sp.get("attrs") or {}).get("stall_s", 0.0) or 0.0)
            split = max(sp["_t0"], sp["_t1"] - stall)
            for a, b in _subtract(sp["_t0"], split, holes):
                add(a, b, "dispatch", str(sp.get("span")))
            add(split, sp["_t1"], "host_stall", str(sp.get("span")))

        if wid is not None and wid in workers:
            node = workers[wid]
            # Spawn: process creation, interpreter + jax import, engine
            # construction — everything between the supervisor's spawn and
            # the worker's first device dispatch, minus the compile and
            # checkpoint spans already attributed above (engine-build
            # compiles land before the first batch; the Python lowering
            # slivers between them are setup too, not mystery time).
            end = min(sp["_t0"] for sp in broad) if broad else node.end_t
            if node.end_reason != "open":
                end = min(end, node.end_t)
            for a, b in _subtract(node.spawn_t, end, holes):
                add(a, b, "spawn", "spawn")

        # Supervisor-side: requeue backoff windows.
        for sp in group:
            if sp.get("span") == "fleet_requeue":
                attrs = sp.get("attrs") or {}
                backoff = float(attrs.get("backoff_s", 0.0) or 0.0)
                if backoff > 0:
                    intervals.append(Interval(
                        sp["_t1"], sp["_t1"] + backoff, "backoff", proc,
                        "fleet_requeue", str(attrs.get("worker") or "") or None,
                    ))
        if "chaos" in names or "fleet_quarantine" in names or "fleet_adopt" in names:
            for sp in group:
                if sp.get("span") in ("chaos", "fleet_quarantine", "fleet_adopt"):
                    instants.append({
                        "t": sp["_t1"], "span": sp.get("span"),
                        "process": proc, "worker": wid,
                        "attrs": sp.get("attrs") or {},
                    })
    intervals.sort(key=lambda iv: (iv.start, iv.end))
    instants.sort(key=lambda e: e["t"])
    return intervals, instants


# ---------------------------------------------------------------------------
# Critical-path attribution.


@dataclasses.dataclass
class Segment:
    start: float
    end: float
    category: str
    worker: str | None
    span: str | None


def critical_path(trace: FleetTrace) -> list[Segment]:
    """Partition the fleet window [t0, t1] into consecutive segments, each
    attributed to ONE categorized interval: walking backward from the end,
    every instant follows the longest-running interval covering it (the
    binding constraint for having reached that instant — the classic
    backward critical-path construction). Gaps no interval covers become
    ``supervisor_idle`` when no worker was alive there, ``unattributed``
    otherwise — the explicit remainder, never silently dropped.

    O(n log n) in the interval count (a season-long fleet's merged ledgers
    hold tens of thousands of batch spans — a rescan per emitted segment
    would be quadratic): as ``cur`` walks backward, intervals activate once
    off an end-sorted list into a start-keyed heap whose minimum IS the
    longest-running cover, and the gap branch bisects the sorted ends.
    Memoized on the trace — the walk is referenced by every render surface.
    """
    if trace._segments is not None:
        return trace._segments
    import bisect
    import heapq

    eps = 1e-9
    ivs = [
        iv for iv in trace.intervals
        if iv.end > iv.start and iv.start < trace.t1 and iv.end > trace.t0
    ]
    by_end_desc = sorted(ivs, key=lambda iv: -iv.end)
    ends_asc = sorted(iv.end for iv in ivs)
    heap: list[tuple[float, int]] = []  # (start, index into by_end_desc)
    nxt = 0  # activation pointer: by_end_desc[:nxt] are in the heap
    alive = [(w.spawn_t, w.end_t) for w in trace.workers.values()]
    segments: list[Segment] = []
    cur = trace.t1
    while cur - trace.t0 > eps:
        while nxt < len(by_end_desc) and by_end_desc[nxt].end >= cur - eps:
            heapq.heappush(heap, (by_end_desc[nxt].start, nxt))
            nxt += 1
        # Active intervals all satisfy end >= cur - eps (activation happened
        # at a cur no smaller than this one); the heap minimum is therefore
        # exactly min(start) over the covering set whenever it clears the
        # start < cur test.
        if heap and heap[0][0] < cur - eps:
            iv = by_end_desc[heap[0][1]]
            start = max(iv.start, trace.t0)
            segments.append(Segment(start, cur, iv.category, iv.worker, iv.span))
            cur = start
            continue
        i = bisect.bisect_left(ends_asc, cur - eps)
        prev_end = ends_asc[i - 1] if i > 0 else trace.t0
        prev_end = max(min(prev_end, cur), trace.t0)
        # Split the gap at worker alive-window edges so one segment never
        # straddles an alive/idle transition and gets misclassified by its
        # midpoint.
        edges = [
            t for w in alive for t in w if prev_end + eps < t < cur - eps
        ]
        start = max(edges) if edges else prev_end
        mid = (start + cur) / 2.0
        worker_alive = any(a <= mid <= b for a, b in alive)
        segments.append(Segment(
            start, cur,
            "unattributed" if worker_alive else "supervisor_idle", None, None,
        ))
        cur = start
    segments.reverse()
    trace._segments = segments
    return segments


def attribution(trace: FleetTrace) -> dict[str, Any]:
    """Per-category wall-clock attribution over the critical path. The
    category seconds sum EXACTLY to the fleet window; ``coverage`` is the
    attributed fraction (1 - unattributed share) — the ci.sh fleet drill
    gates it at >= 0.9."""
    segs = critical_path(trace)
    per: dict[str, float] = {c: 0.0 for c in CATEGORIES}
    for seg in segs:
        per[seg.category] = per.get(seg.category, 0.0) + (seg.end - seg.start)
    total = max(trace.t1 - trace.t0, 1e-12)
    return {
        "total_s": round(total, 6),
        "categories": {c: round(s, 6) for c, s in per.items()},
        "coverage": round(1.0 - per.get("unattributed", 0.0) / total, 6),
        "segments": len(segs),
    }


def worker_utilization(trace: FleetTrace) -> list[dict[str, Any]]:
    """Per-worker occupancy rows: alive window (spawn -> done/requeue), busy
    seconds by category from the worker's own process intervals (clipped to
    the alive window), and the busy fraction. Workers whose telemetry ledger
    was not collected (or never existed) report ``busy_s=None`` — lease-level
    only, which is what ``tpusim watch`` renders live from the supervisor
    ledger alone."""
    rows = []
    for wid in sorted(trace.workers):
        node = trace.workers[wid]
        alive = max(node.end_t - node.spawn_t, 0.0)
        busy: dict[str, float] | None = None
        if node.process is not None:
            busy = defaultdict(float)
            for iv in trace.intervals:
                if iv.worker != wid or iv.category == "backoff":
                    continue
                lo = max(iv.start, node.spawn_t)
                hi = min(iv.end, node.end_t) if node.end_reason != "open" else iv.end
                if hi > lo:
                    busy[iv.category] += hi - lo
        busy_s = round(sum(busy.values()), 6) if busy is not None else None
        rows.append({
            "worker": wid, "point": node.point, "attempt": node.attempt,
            "end_reason": node.end_reason,
            "alive_s": round(alive, 6),
            "busy_s": busy_s,
            "utilization": (
                round(min(busy_s / alive, 1.0), 4)
                if busy_s is not None and alive > 0 else None
            ),
            "by_category": (
                {c: round(s, 6) for c, s in sorted(busy.items())}
                if busy is not None else None
            ),
        })
    return rows


# ---------------------------------------------------------------------------
# Perfetto export (shared schema gate lives here so the exporter, CI and the
# artifact harvest stay jax-free; tpusim.flight_export re-exports both names
# for its existing consumers).


def validate_perfetto(trace: Any) -> int:
    """Schema check for an exported chrome-trace/Perfetto JSON (the device
    flight traces AND the orchestration timeline ride the same gate): raises
    ValueError on any violation, returns the number of non-metadata events."""
    if not isinstance(trace, dict) or not isinstance(trace.get("traceEvents"), list):
        raise ValueError("trace must be a dict with a traceEvents list")
    n = 0
    for ev in trace["traceEvents"]:
        if not isinstance(ev, dict) or "ph" not in ev:
            raise ValueError(f"trace event without ph: {ev!r}")
        if ev["ph"] == "M":
            if "name" not in ev:
                raise ValueError(f"metadata event without name: {ev!r}")
            continue
        if ev["ph"] not in ("i", "I", "X"):
            raise ValueError(f"unexpected phase {ev['ph']!r}")
        if not isinstance(ev.get("ts"), (int, float)) or ev["ts"] < 0:
            raise ValueError(f"event without numeric ts: {ev!r}")
        if not isinstance(ev.get("pid"), int) or not isinstance(ev.get("tid"), int):
            raise ValueError(f"event without integer pid/tid: {ev!r}")
        if ev["ph"] == "i" and ev.get("s") not in ("t", "p", "g"):
            raise ValueError(f"instant event without scope: {ev!r}")
        if ev["ph"] == "X" and (
            not isinstance(ev.get("dur"), (int, float)) or ev["dur"] < 0
        ):
            raise ValueError(f"complete event without numeric dur: {ev!r}")
        if not isinstance(ev.get("name"), str):
            raise ValueError(f"event without name: {ev!r}")
        n += 1
    return n


def _write_artifact(path: Path, text: str) -> None:
    """Write one export artifact, failing CLEAN on a torn write: a half-
    written trace JSON (ENOSPC, yanked volume) parses as nothing yet still
    looks like a deliverable, so the partial file is removed and the error
    reported as one line instead of a stack trace."""
    path.parent.mkdir(parents=True, exist_ok=True)
    try:
        path.write_text(text)
    except OSError as e:
        try:
            path.unlink(missing_ok=True)
        except OSError:
            pass
        raise SystemExit(
            f"error: writing {path} failed ({e}); partial file removed"
        ) from None


def perfetto_timeline(trace: FleetTrace) -> dict:
    """The orchestration chrome-trace: pid 0 = the supervisor (backoff
    slices + chaos/quarantine/adopt instants), one pid per worker with a
    ``lease`` track (tid 0), a ``work`` track (spawn/dispatch/stall slices,
    tid 1) and a ``host`` track (compile/checkpoint slices, tid 2). ``ts``
    is microseconds since the fleet window start, on the rebased supervisor
    clock — so it loads next to a device flight trace without either lying
    about wall order."""
    base = trace.t0
    us = lambda t: max(round((t - base) * 1e6), 0)
    tev: list[dict] = [
        {"ph": "M", "name": "process_name", "pid": 0,
         "args": {"name": "fleet supervisor"}},
        {"ph": "M", "name": "thread_name", "pid": 0, "tid": 0,
         "args": {"name": "queue"}},
    ]
    pid_of: dict[str, int] = {}
    for i, wid in enumerate(sorted(trace.workers)):
        node = trace.workers[wid]
        pid = i + 1
        pid_of[wid] = pid
        tev.append({"ph": "M", "name": "process_name", "pid": pid,
                    "args": {"name": f"{wid} {node.point}"}})
        for tid, name in ((0, "lease"), (1, "work"), (2, "host")):
            tev.append({"ph": "M", "name": "thread_name", "pid": pid,
                        "tid": tid, "args": {"name": name}})
        tev.append({
            "name": f"lease {node.point}", "ph": "X",
            "ts": us(node.spawn_t), "dur": max(round((node.end_t - node.spawn_t) * 1e6), 0),
            "pid": pid, "tid": 0,
            "args": {"attempt": node.attempt, "end": node.end_reason},
        })
    _HOST = ("compile", "checkpoint")
    for iv in trace.intervals:
        dur = max(round((iv.end - iv.start) * 1e6), 0)
        if iv.category == "backoff":
            tev.append({
                "name": "requeue backoff", "ph": "X", "ts": us(iv.start),
                "dur": dur, "pid": 0, "tid": 0,
                "args": {"worker": iv.worker},
            })
            continue
        pid = pid_of.get(iv.worker or "")
        if pid is None:
            continue
        tev.append({
            "name": iv.category if iv.category != "dispatch" else iv.span,
            "ph": "X", "ts": us(iv.start), "dur": dur,
            "pid": pid, "tid": 2 if iv.category in _HOST else 1,
            "args": {"category": iv.category},
        })
    for inst in trace.instants:
        pid = pid_of.get(inst.get("worker") or "", 0)
        attrs = inst.get("attrs") or {}
        name = str(inst["span"])
        if name == "chaos":
            name = f"chaos {attrs.get('point', '?')}/{attrs.get('kind', '?')}"
        tev.append({
            "name": name, "ph": "i", "s": "p",
            "ts": us(inst["t"]), "pid": pid, "tid": 0,
            "args": {str(k): str(v) for k, v in attrs.items()},
        })
    att = attribution(trace)
    return {
        "traceEvents": tev,
        "displayTimeUnit": "ms",
        "otherData": {
            "tool": "tpusim trace timeline",
            "trace_id": trace.trace_id,
            **({"run_id": trace.run_id} if trace.run_id else {}),
            "workers": len(trace.workers),
            "attribution": att,
        },
    }


# ---------------------------------------------------------------------------
# Text/markdown rendering + CLI.

#: Shared panel shapes: `tpusim trace timeline` and the `tpusim report`
#: fleet panels render the SAME row builders (summarize_fleet_spans'
#: one-extraction discipline), so the two surfaces cannot drift on a
#: category or column change.
ATTRIBUTION_HEADERS = ["category", "wall-clock", "share"]
UTILIZATION_HEADERS = [
    "worker", "point", "attempt", "end", "alive", "busy", "util",
    "top categories",
]


def attribution_rows(att: dict[str, Any]) -> list[list[str]]:
    from .report import _fmt_s  # jax-free; lazy against the import cycle

    total = att["total_s"] or 1e-12
    return [
        [cat, _fmt_s(secs), f"{100.0 * secs / total:.1f}%"]
        for cat, secs in att["categories"].items() if secs > 0
    ]


def attribution_footer(att: dict[str, Any]) -> str:
    from .report import _fmt_s

    return (
        f"attributed {100.0 * att['coverage']:.1f}% of "
        f"{_fmt_s(att['total_s'])} fleet wall-clock; remainder reported "
        f"as unattributed"
    )


def utilization_rows(trace: FleetTrace) -> list[list[str]]:
    from .report import _fmt_s

    rows = []
    for r in worker_utilization(trace):
        cats = r["by_category"]
        top = (
            ", ".join(
                f"{c} {_fmt_s(s)}"
                for c, s in sorted(cats.items(), key=lambda kv: -kv[1])[:3]
            )
            if cats else "(no worker ledger)"
        )
        rows.append([
            r["worker"], r["point"], str(r["attempt"]), r["end_reason"],
            _fmt_s(r["alive_s"]),
            _fmt_s(r["busy_s"]) if r["busy_s"] is not None else "n/a",
            f"{100.0 * r['utilization']:.0f}%"
            if r["utilization"] is not None else "n/a",
            top,
        ])
    return rows


def render_timeline(trace: FleetTrace, fmt: str = "text") -> str:
    """The attribution dashboard for one assembled fleet trace — the text
    twin of the Perfetto export, shared by ``tpusim trace timeline`` and the
    ``tpusim report`` fleet-attribution panel."""
    from .report import _fmt_s, text_table  # jax-free; lazy vs the cycle

    md = fmt == "md"
    out: list[str] = []

    def heading(text: str) -> None:
        out.append(f"\n## {text}\n" if md else f"\n== {text} ==")

    def table(headers: list[str], rows: list[list[str]]) -> None:
        if md:
            out.append("| " + " | ".join(headers) + " |")
            out.append("|" + "|".join("---" for _ in headers) + "|")
            for r in rows:
                out.append("| " + " | ".join(r) + " |")
        else:
            out.extend(text_table(headers, rows))

    att = attribution(trace)
    skewed = [
        (proc, meta["skew_s"]) for proc, meta in trace.processes.items()
        if meta.get("skew_s")
    ]
    title = "tpusim orchestration timeline"
    out.append(f"# {title}" if md else title)
    out.append(
        f"trace {trace.trace_id}"
        + (f" (run_id {trace.run_id})" if trace.run_id else "")
        + f" · {len(trace.workers)} worker(s) · fleet wall-clock "
        + _fmt_s(att['total_s'])
    )
    if skewed:
        out.append(
            "clock skew corrected: "
            + ", ".join(f"{proc} +{s:.3f}s" for proc, s in skewed)
        )

    heading("Wall-clock attribution (critical path)")
    table(ATTRIBUTION_HEADERS, attribution_rows(att))
    out.append("  " + attribution_footer(att))

    heading("Per-worker utilization")
    table(UTILIZATION_HEADERS, utilization_rows(trace))

    segs = critical_path(trace)
    heading("Critical path (longest segments)")
    longest = sorted(segs, key=lambda s: s.start)
    top = sorted(longest, key=lambda s: -(s.end - s.start))[:12]
    keep = {id(s) for s in top}
    rows = [
        [f"+{seg.start - trace.t0:.2f}s", _fmt_s(seg.end - seg.start),
         seg.category, seg.worker or "-", seg.span or "-"]
        for seg in longest if id(seg) in keep
    ]
    table(["at", "length", "category", "worker", "span"], rows)

    if trace.instants:
        heading("Faults & quarantines")
        rows = [
            [f"+{inst['t'] - trace.t0:.2f}s", str(inst["span"]),
             str(inst.get("worker") or "-"),
             ", ".join(f"{k}={v}" for k, v in (inst["attrs"] or {}).items()
                       if k not in ("leases",))[:80] or "-"]
            for inst in trace.instants
        ]
        table(["at", "event", "worker", "context"], rows)
    return "\n".join(out) + "\n"


def timeline_main(argv: list[str] | None = None) -> int:
    """``tpusim trace timeline``: merge every telemetry ledger under a fleet
    state dir (plus any extra ledgers), assemble the cross-process span tree,
    print the attribution dashboard and export the orchestration Perfetto
    trace. Exit 2 when no correlatable fleet trace is found."""
    ap = argparse.ArgumentParser(
        prog="tpusim trace timeline",
        description="Cross-process fleet timeline: critical-path wall-clock "
        "attribution + orchestration Perfetto export from the telemetry "
        "ledgers under a fleet state dir.",
    )
    ap.add_argument(
        "sources", nargs="+", type=Path,
        help="fleet state dir(s) (scanned recursively for *.jsonl ledgers) "
        "and/or individual telemetry ledger files",
    )
    ap.add_argument(
        "--out", type=Path, default=None, metavar="JSON",
        help="write the orchestration Perfetto trace here "
        "(load in ui.perfetto.dev)",
    )
    ap.add_argument("--format", choices=("text", "md"), default="text")
    ap.add_argument(
        "--trace-id", default=None,
        help="pin one trace (default: the newest with fleet_spawn spans)",
    )
    args = ap.parse_args(argv)

    missing = [str(p) for p in args.sources if not p.exists()]
    if missing:
        print(f"error: {', '.join(missing)} does not exist", file=sys.stderr)
        return 2
    spans = collect_spans(args.sources)
    trace = assemble(spans, trace_id=args.trace_id)
    if trace is None:
        print(
            "error: no fleet trace found (the ledgers carry no fleet_spawn "
            "spans — run the fleet with --telemetry)", file=sys.stderr,
        )
        return 2
    try:
        print(render_timeline(trace, fmt=args.format), end="", flush=True)
    except BrokenPipeError:
        pass
    if args.out is not None:
        exported = perfetto_timeline(trace)
        validate_perfetto(exported)
        _write_artifact(args.out, json.dumps(exported))
        att = exported["otherData"]["attribution"]
        print(
            # stderr: the rendered report owns stdout (`> timeline.md` must
            # capture the dashboard alone, notice excluded).
            f"[timeline] wrote {args.out} ({len(exported['traceEvents'])} "
            f"events, {100.0 * att['coverage']:.1f}% attributed; open in "
            f"ui.perfetto.dev)", file=sys.stderr,
        )
    return 0


if __name__ == "__main__":
    sys.exit(timeline_main())
