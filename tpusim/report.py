"""``tpusim report`` — render a telemetry ledger into a dashboard.

Three input kinds, auto-detected:

  * a telemetry **JSONL file** written by ``--telemetry`` (tpusim.telemetry):
    rendered into a terminal/markdown dashboard — phase breakdown, steady-
    state throughput (the same derivation as ``Profiler.report``:
    telemetry.throughput_report; single-batch ledgers render a flagged
    compile-contaminated estimate), a pipelined-dispatch stall histogram,
    the device-side simulation counters (max reorg depth, stale events,
    active-step occupancy) aggregated over every batch span, the
    compile/engine-cache and device-memory panels (the ``compile`` /
    ``engine_cache`` spans and per-batch memory watermarks of
    tpusim.telemetry.CompileLedger / device_memory_attrs), and — when the
    ledger carries the runner's per-batch ``stats`` spans
    (tpusim.convergence) — the convergence panels: final CI half-widths per
    statistic, the ETA-to-target extrapolation, and the CI-narrowing
    trajectory across batches. ``tpusim watch`` is this dashboard's live
    twin for a still-growing ledger;
  * a **fleet state dir** (any directory WITHOUT XLA trace files): every
    ``*.jsonl`` telemetry ledger under it — the supervisor's plus each
    worker's — is merged (deduplicated) into one dashboard. A traced fleet
    shares one ``run_id`` across all its processes (tpusim.tracing), so the
    throughput/convergence panels partition by ``(run_id, process)``, and
    the fleet panel grows the cross-process time-attribution and per-worker
    utilization tables;
  * an XLA **trace directory** written by ``--trace-dir``: offline op-level
    time attribution from the chrome-trace JSON inside — no TensorBoard
    needed (absorbed from the former scripts/trace_report.py; that script is
    now a thin shim over this module). Attribution is meaningful on DEVICE
    tracks (flat, non-overlapping op spans); host Python tracks nest caller
    inside callee, so their sums overcount — device tracks are preferred
    automatically when present.

    python -m tpusim report artifacts/telemetry/run.jsonl [--format md]
    python -m tpusim report artifacts/trace_fast_r5 [--top 25]
"""

from __future__ import annotations

import argparse
import gzip
import json
import sys
from collections import defaultdict
from pathlib import Path
from typing import Any

from .telemetry import BatchRecord, load_spans, throughput_report

__all__ = ["render_report", "trace_attribution", "text_table", "format_bytes", "main"]


# ---------------------------------------------------------------------------
# Telemetry JSONL dashboard.

#: Stall histogram bucket upper bounds in seconds (log-ish ladder); the last
#: bucket is open-ended.
_STALL_BUCKETS = (0.001, 0.01, 0.1, 1.0, 10.0)


def _fmt_s(s: float) -> str:
    return f"{s * 1e3:.1f} ms" if s < 1.0 else f"{s:.2f} s"


def format_bytes(n: int | float) -> str:
    n = float(n)
    if n >= 1e9:
        return f"{n / 1e9:.2f} GB"
    if n >= 1e6:
        return f"{n / 1e6:.2f} MB"
    if n >= 1e3:
        return f"{n / 1e3:.1f} KB"
    return f"{int(n)} B"


def text_table(headers: list[str], rows: list[list[str]]) -> list[str]:
    """Column-aligned plain-text table lines — the one text renderer behind
    this dashboard's tables AND `tpusim watch`'s (which imports it), so the
    two surfaces keep one look."""
    widths = [
        max(len(h), *(len(r[i]) for r in rows)) if rows else len(h)
        for i, h in enumerate(headers)
    ]
    out = ["  " + "  ".join(h.ljust(w) for h, w in zip(headers, widths))]
    for r in rows:
        out.append("  " + "  ".join(c.ljust(w) for c, w in zip(r, widths)))
    return out


def _bar(count: int, peak: int, width: int = 24) -> str:
    if peak <= 0:
        return ""
    return "#" * max(1, round(width * count / peak)) if count else ""


def _stall_histogram(stalls: list[float]) -> list[tuple[str, int]]:
    edges = [0.0, *_STALL_BUCKETS, float("inf")]
    labels = []
    counts = []
    for lo, hi in zip(edges[:-1], edges[1:]):
        n = sum(1 for s in stalls if lo <= s < hi)
        hi_lbl = "inf" if hi == float("inf") else _fmt_s(hi)
        labels.append(f"{_fmt_s(lo)} - {hi_lbl}" if lo else f"< {hi_lbl}")
        counts.append(n)
    return list(zip(labels, counts))


def _group_key(sp: dict) -> tuple[str, str]:
    """The per-run partition key of the derived panels: ``(run_id,
    process)``. One traced fleet shares one run_id across the supervisor and
    every worker (tpusim.tracing), so run_id alone would blend N processes'
    span streams; versionless spans (no ``process``) key on ``""`` and group
    exactly as before."""
    return str(sp.get("run_id", "?")), str(sp.get("process") or "")


def _group_label(key: tuple[str, str], groups: dict) -> str:
    rid, proc = key
    same_rid = sum(1 for k in groups if k[0] == rid)
    return f"{rid} · {proc}" if proc and same_rid > 1 else rid


def _phase_rows(spans: list[dict]) -> list[tuple[str, int, float]]:
    """(span name, count, total duration) sorted by total duration."""
    totals: dict[str, float] = defaultdict(float)
    counts: dict[str, int] = defaultdict(int)
    for sp in spans:
        totals[sp["span"]] += float(sp.get("dur_s") or 0.0)
        counts[sp["span"]] += 1
    return sorted(
        ((name, counts[name], totals[name]) for name in totals),
        key=lambda row: -row[2],
    )


def _sum_vectors(a: list[int] | None, b: list) -> list[int]:
    """Elementwise sum tolerating length drift across appended ledgers (a
    roster change between runs writing to one file)."""
    vb = [int(x) for x in b]
    if a is None:
        return vb
    if len(vb) < len(a):
        vb += [0] * (len(a) - len(vb))
    for i, x in enumerate(a):
        vb[i] += x
    return vb


def _batch_aggregates(batches: list[dict]) -> dict[str, Any] | None:
    """Fold the device-side counters riding in batch-span attrs into the
    run-level summary (max of maxes, sum of sums — including the per-miner
    stale and reorg-depth histograms — and traffic-weighted occupancy).
    Batches recorded without counters (e.g. a foreign emitter) simply don't
    contribute."""
    agg: dict[str, Any] = {
        "reorg_depth_max": 0, "stale_events": 0,
        "active_steps": 0, "step_slots": 0, "retries": 0,
        "stale_by_miner": None, "reorg_depth_hist": None,
    }
    seen = False
    for sp in batches:
        attrs = sp.get("attrs") or {}
        if attrs.get("reorg_depth_max") is not None:
            seen = True
            agg["reorg_depth_max"] = max(
                agg["reorg_depth_max"], int(attrs.get("reorg_depth_max") or 0)
            )
            agg["stale_events"] += int(attrs.get("stale_events") or 0)
            agg["active_steps"] += int(attrs.get("active_steps") or 0)
            agg["step_slots"] += int(attrs.get("step_slots") or 0)
            for name in ("stale_by_miner", "reorg_depth_hist"):
                vec = attrs.get(name)
                if isinstance(vec, list):
                    agg[name] = _sum_vectors(agg[name], vec)
        # `or 0`, not a .get default: a foreign ledger can carry the KEY with
        # a null value, and int(None) would crash the dashboard.
        agg["retries"] += int(attrs.get("retries") or 0)
    if not seen:
        return None
    agg["occupancy"] = (
        agg["active_steps"] / agg["step_slots"] if agg["step_slots"] else None
    )
    return agg


def render_report(spans: list[dict], fmt: str = "text", slo=None, lineage=None) -> str:
    """The dashboard string for one telemetry ledger (``fmt``: text | md).

    ``slo`` is an optional list of :class:`tpusim.metrics.Objective`; when
    given, an "SLO status" panel renders the SAME shared evaluator
    (``tpusim.metrics.evaluate_slos``) that ``tpusim slo check`` gates on —
    one source of truth, no drifting twin renderers. The panel is
    span-scoped (objectives over perf-ledger metrics show NO-DATA here; the
    gate's full state-dir derivation lives in ``slo check``).

    ``lineage`` is an optional :func:`tpusim.provenance.summarize_lineage`
    digest; when given, a provenance panel shows the lineage ledger next to
    the spans it cross-checks (``tpusim audit`` is the gate; this is the
    glance)."""
    md = fmt == "md"
    out: list[str] = []

    def heading(text: str) -> None:
        if md:
            out.append(f"\n## {text}\n")
        else:
            out.append(f"\n== {text} ==")

    def table(headers: list[str], rows: list[list[str]]) -> None:
        if md:
            out.append("| " + " | ".join(headers) + " |")
            out.append("|" + "|".join("---" for _ in headers) + "|")
            for r in rows:
                out.append("| " + " | ".join(r) + " |")
        else:
            out.extend(text_table(headers, rows))

    if not spans:
        return "telemetry ledger is empty (no parseable spans)\n"

    # str-normalized: a foreign row with "run_id": null must not poison the
    # sort (None vs str comparison) — same null class as the attr guards.
    run_ids = sorted({str(sp.get("run_id") or "?") for sp in spans})
    t0 = min((sp.get("t_start") or 0.0) for sp in spans)
    t1 = max((sp.get("t_start") or 0.0) + (sp.get("dur_s") or 0.0) for sp in spans)
    title = "tpusim telemetry report"
    out.append(f"# {title}" if md else title)
    out.append(
        f"{len(spans)} spans, run_id{'s' if len(run_ids) > 1 else ''} "
        f"{', '.join(run_ids)}, wall window {t1 - t0:.2f} s"
    )

    heading("Phase breakdown")
    rows = _phase_rows(spans)
    grand = sum(r[2] for r in rows) or 1e-12
    table(
        ["span", "count", "total", "share"],
        [
            [name, str(cnt), _fmt_s(tot), f"{100 * tot / grand:.1f}%"]
            for name, cnt, tot in rows
        ],
    )

    batches = [sp for sp in spans if sp["span"] == "batch"]
    if not batches:
        # Spans-only or foreign ledger (e.g. checkpoint/trace spans alone):
        # the derived panels have nothing to derive from — say so instead of
        # assuming batch spans exist.
        heading("Throughput (batch spans)")
        out.append("  no data — ledger has no batch spans")
    if batches:
        # An appended ledger can hold several runs (repeated --telemetry to
        # one file); throughput must derive per (run_id, process) — the
        # first-batch (compile) exclusion and the duration_ms lookup are
        # per-run facts, and mixing runs would count every later run's
        # compile batch as steady state. The process half of the key exists
        # for MERGED fleet ledgers: every worker of a traced fleet shares
        # the supervisor's run_id (tpusim.tracing), so a bare run_id group
        # would interleave N workers' batch streams into one bogus record
        # list — and double-count every repeated (healed) point's work.
        run_attrs = {
            _group_key(sp): sp.get("attrs", {})
            for sp in spans if sp["span"] == "run"
        }
        groups: dict[tuple[str, str], list[dict]] = {}
        for sp in batches:
            groups.setdefault(_group_key(sp), []).append(sp)
        for key, group in groups.items():
            heading(
                "Throughput (batch spans)" if len(groups) == 1
                else f"Throughput — run {_group_label(key, groups)}"
            )
            records = [
                BatchRecord(
                    int((sp.get("attrs") or {}).get("runs") or 0),
                    float(sp.get("dur_s") or 0.0),
                )
                for sp in group
            ]
            a = run_attrs.get(key, {})
            # duration_ms/block_interval_s ride on the run span; without one
            # (partial ledger) only run-rate is derivable.
            if a.get("duration_ms") is not None:
                rep = throughput_report(
                    records, int(a.get("duration_ms") or 0),
                    float(a.get("block_interval_s") or 600.0),
                )
            else:
                rep = throughput_report(records, 0, 600.0)
                rep.pop("steady_sim_years_per_s", None)
                rep.pop("steady_events_per_s", None)
            table(
                ["metric", "value"],
                [[k, json.dumps(v)] for k, v in rep.items()],
            )
            if rep.get("steady_is_first_batch"):
                # A single-batch ledger has only compile-contaminated
                # numbers; render them flagged in prose, not merely as a
                # table row someone has to know to look for.
                out.append(
                    "  single-batch ledger: the steady-state rows above reuse "
                    "the compile-contaminated first batch"
                )

        stalls = [
            float((sp.get("attrs") or {}).get("stall_s") or 0.0)
            for sp in batches
            if (sp.get("attrs") or {}).get("stall_s") is not None
        ]
        heading("Pipelined-dispatch stall histogram")
        if stalls:
            hist = _stall_histogram(stalls)
            peak = max(c for _, c in hist)
            table(
                ["stall", "batches", ""],
                [[lbl, str(c), _bar(c, peak)] for lbl, c in hist],
            )
        else:
            out.append("  no data — batch spans carry no stall_s attr")

        agg = _batch_aggregates(batches)
        if agg is not None:
            heading("Simulation counters (device-side)")
            occ = agg["occupancy"]
            table(
                ["counter", "value"],
                [
                    ["max reorg depth (own blocks popped, single reorg)",
                     str(agg["reorg_depth_max"])],
                    ["stale events (events losing >=1 block)",
                     str(agg["stale_events"])],
                    ["active step occupancy (active / executed step slots)",
                     f"{occ:.4f}" if occ is not None else "n/a"],
                    ["batch retries", str(agg["retries"])],
                ],
            )

            # Histogram panels (PR 2's scalars collapsed everything to
            # max/sum; the device counters now keep the distributions).
            sbm = agg.get("stale_by_miner")
            if sbm:
                heading("Stale events by miner")
                peak = max(sbm)
                table(
                    ["miner", "stale events", ""],
                    [[str(i), str(c), _bar(c, peak)] for i, c in enumerate(sbm)],
                )
            rdh = agg.get("reorg_depth_hist")
            if rdh:
                heading("Reorg depth histogram")
                peak = max(rdh)
                table(
                    ["depth (own blocks popped)", "events", ""],
                    [
                        [f"{d + 1}{'+' if d == len(rdh) - 1 else ''}",
                         str(c), _bar(c, peak)]
                        for d, c in enumerate(rdh)
                    ],
                )

    compiles = [sp for sp in spans if sp["span"] == "compile"]
    cache_sp = [sp for sp in spans if sp["span"] == "engine_cache"]
    if compiles or cache_sp:
        # Compile & engine-cache observability (tpusim.telemetry.CompileLedger):
        # every XLA backend compile the run paid for, with the dispatch
        # context the ledger narrated, plus the make_engine cache counters —
        # a sweep whose grid points recompile shows up HERE, not only in a
        # test someone remembers to run.
        heading("XLA compiles & engine cache")
        durs = [float(sp.get("dur_s") or 0.0) for sp in compiles]
        rows = [
            ["backend compiles", str(len(compiles))],
            ["compile time (monitored events)", _fmt_s(sum(durs))],
        ]
        if durs:
            rows.append(["slowest compile", _fmt_s(max(durs))])
        if cache_sp:
            hits = sum(
                1 for sp in cache_sp if (sp.get("attrs") or {}).get("hit")
            )
            rows.append(
                ["engine-cache lookups (hit / miss)",
                 f"{hits} / {len(cache_sp) - hits}"]
            )
        table(["counter", "value"], rows)
        by_ctx: dict[tuple[str, str], list[float]] = defaultdict(list)
        for sp in compiles:
            attrs = sp.get("attrs") or {}
            by_ctx[
                (str(attrs.get("engine", "?")),
                 str(attrs.get("dispatch", "build")))
            ].append(float(sp.get("dur_s") or 0.0))
        if by_ctx:
            table(
                ["engine", "dispatch context", "compiles", "total"],
                [
                    [eng, ctx, str(len(ds)), _fmt_s(sum(ds))]
                    for (eng, ctx), ds in sorted(
                        by_ctx.items(), key=lambda kv: -sum(kv[1])
                    )
                ],
            )

    mem_attrs = [
        sp.get("attrs") or {}
        for sp in spans
        if sp["span"] == "batch" and "mem_live_bytes" in (sp.get("attrs") or {})
    ]
    if mem_attrs:
        # Per-batch memory watermarks (telemetry.device_memory_attrs + the
        # engine's static footprint model): worst over the run.
        heading("Device memory (batch watermarks)")
        rows = [
            ["live-buffer watermark (jax.live_arrays)",
             format_bytes(max(a.get("mem_live_bytes") or 0 for a in mem_attrs))],
            ["live buffers (max)",
             str(max(int(a.get("mem_live_buffers") or 0) for a in mem_attrs))],
        ]
        peaks = [
            a.get("mem_peak_bytes") for a in mem_attrs
            if a.get("mem_peak_bytes") is not None
        ]
        if peaks:
            rows.append(["allocator peak (memory_stats)", format_bytes(max(peaks))])
        last = mem_attrs[-1]
        state_bytes = last.get("state_bytes_per_run")
        if state_bytes is not None:
            rows.append(
                ["state bytes per run (dtype-resolved)",
                 format_bytes(state_bytes)]
            )
        est = last.get("vmem_est_bytes")
        if est is not None:
            budget = last.get("vmem_budget_bytes")
            val = format_bytes(est)
            if budget:
                val += f" of {format_bytes(budget)} budget ({100 * est / budget:.0f}%)"
            rows.append(["kernel VMEM estimate", val])
        table(["counter", "value"], rows)

    sstats = [sp for sp in spans if sp["span"] == "stats"]
    if sstats:
        # Convergence panels (the per-batch `stats` spans of
        # tpusim.convergence): final CI state + the narrowing trajectory.
        # Grouped per (run_id, process) like throughput — an appended ledger
        # (or a sweep, which shares one run_id across points) renders each
        # segment's own trajectory, a merged fleet ledger each WORKER's own
        # (they share the supervisor's run_id); a run-count drop inside one
        # group marks a new accumulator (next sweep point).
        from .convergence import format_num, point_snapshot_rows, snapshot_rows

        sgroups: dict[tuple[str, str], list[dict]] = {}
        for sp in sstats:
            sgroups.setdefault(_group_key(sp), []).append(sp)
        for key, group in sgroups.items():
            rid = _group_label(key, sgroups)
            prow = point_snapshot_rows(group)
            if prow is not None:
                # Packed sweep: the spans are per-POINT segments
                # (tpusim.packed) — render per-point CI narrowing instead of
                # one blended run.
                heading(
                    "Convergence by grid point (packed sweep)"
                    if len(sgroups) == 1
                    else f"Convergence by grid point — run {rid}"
                )
                table(["point", "runs", "rel hw95 (worst stat)", "status"], prow)
                # A MIXED sweep also carries plain spans from unpackable
                # fallback points (they ran through the runner) — their
                # blended panel renders below from its own span subset.
                group = [
                    sp for sp in group
                    if not isinstance((sp.get("attrs") or {}).get("point"), str)
                ]
                if not group:
                    continue
            a = group[-1].get("attrs") or {}
            heading(
                "Convergence (stats spans)" if len(sgroups) == 1
                else f"Convergence — run {rid}"
            )
            line = f"{a.get('runs', '?')} runs folded"
            if a.get("runs_done") is not None and a.get("runs_done") != a.get("runs"):
                line += f" (run at {a.get('runs_done')} incl. resumed checkpoint)"
            if a.get("runs_total"):
                line += f" of {a.get('runs_total')} planned"
            if a.get("target_rel_hw") is not None:
                line += f"; target rel half-width {format_num(a.get('target_rel_hw'))}"
            if a.get("rate_is_first_batch"):
                line += "; ETA rate from the compile-contaminated first batch"
            out.append("  " + line)
            table(
                ["stat", "rel hw95 (worst miner)", "hw95 (max)", "eta to target"],
                snapshot_rows(a.get("stats") or {}),
            )

            heading(
                "CI narrowing (rel half-width vs batch)" if len(sgroups) == 1
                else f"CI narrowing — run {rid}"
            )
            stat_names = list(a.get("stats") or {})
            traj = []
            for sp in group:
                sa = sp.get("attrs") or {}
                row = [str(sa.get("runs", "?"))]
                for stat in stat_names:
                    entry = (sa.get("stats") or {}).get(stat)
                    if not isinstance(entry, dict):  # foreign/partial entry
                        entry = {}
                    row.append(format_num(entry.get("rel_hw_max")))
                traj.append(row)
            table(["runs", *stat_names], traj)

    from .fleet import summarize_fleet_spans

    fleet = summarize_fleet_spans(spans)
    if fleet is not None:
        # Fleet supervisor panels (tpusim.fleet): worker lifecycle, lease
        # state and the requeue/quarantine ledger of an elastic sweep —
        # extracted by the SAME summarizer `tpusim watch` renders from.
        heading("Fleet (worker supervisor)")
        rows = [
            ["points done",
             f"{fleet['points_done']}"
             + (f" / {fleet['points_total']}" if fleet["points_total"] else "")],
            ["workers spawned", str(fleet["spawns"])],
            ["workers alive (last status)",
             str(fleet["workers_alive"] if fleet["workers_alive"] is not None else "n/a")],
            ["requeues", str(len(fleet["requeues"]))],
            ["orphaned leases adopted", str(fleet["adopts"])],
            ["quarantined", ", ".join(fleet["quarantined"]) or "none"],
        ]
        table(["counter", "value"], rows)
        if fleet["requeues"]:
            table(
                ["requeued point", "worker", "reason", "failures", "backoff"],
                [
                    [str(a.get("target", "?")), str(a.get("worker")),
                     str(a.get("reason", "?")), str(a.get("failures", "?")),
                     f"{a.get('backoff_s', 0)} s"]
                    for a in fleet["requeues"]
                ],
            )
        if fleet["leases"]:
            table(
                ["leased point (last status)", "worker", "attempt", "beat age", "progress"],
                [
                    [str(l.get("point", "?")), str(l.get("worker", "?")),
                     str(l.get("attempt", "?")), f"{l.get('age_s', '?')} s",
                     (f"{l['runs_done']}/{l.get('runs_total', '?')}"
                      if l.get("runs_done") is not None else "n/a")]
                    for l in fleet["leases"]
                ],
            )

        # Cross-process time attribution (tpusim.tracing): where the fleet's
        # wall-clock went, on the critical path — spawn/compile/dispatch/
        # stall/checkpoint/backoff/idle, remainder explicit — plus per-worker
        # occupancy. Full category detail needs the worker ledgers merged in
        # (`tpusim report STATE_DIR`); a supervisor-only ledger still gets
        # the lease-level utilization rows. The row builders are SHARED with
        # `tpusim trace timeline` (tpusim.tracing), so the two surfaces
        # cannot drift.
        from .tracing import (
            ATTRIBUTION_HEADERS,
            UTILIZATION_HEADERS,
            assemble,
            attribution,
            attribution_footer,
            attribution_rows,
            utilization_rows,
        )

        trace = assemble(spans)
        if trace is not None:
            correlated = any(
                node.process is not None for node in trace.workers.values()
            )
            if correlated:
                att = attribution(trace)
                heading("Fleet time attribution (critical path)")
                table(ATTRIBUTION_HEADERS, attribution_rows(att))
                out.append("  " + attribution_footer(att))
            heading("Per-worker utilization")
            table(UTILIZATION_HEADERS, utilization_rows(trace))

    if slo:
        from .metrics import (
            SLO_HEADERS,
            evaluate_slos,
            slo_rows,
            snapshot_from_spans,
        )

        heading("SLO status")
        table(SLO_HEADERS, slo_rows(evaluate_slos(slo, snapshot_from_spans(spans))))

    if lineage:
        # Provenance digest (tpusim.provenance): what the lineage ledger
        # recorded alongside these spans — the audit gate's raw material,
        # summarized by the SAME digest `tpusim watch` renders from.
        heading("Provenance (lineage ledger)")
        rows = [
            ["lineage records", str(lineage["records"])],
            ["parent edges (DAG)", str(lineage["edges"])],
            ["dirty-tree records", str(lineage["dirty_records"])],
        ]
        rows += [
            [f"kind `{k}`", str(n)] for k, n in sorted(lineage["kinds"].items())
        ]
        table(["counter", "value"], rows)

    faults = [sp for sp in spans if sp["span"] == "chaos"]
    if faults:
        # The fault ledger: every injected fault of a chaos drill
        # (tpusim.chaos), in firing order, next to the retries/fallbacks it
        # provoked in the phase breakdown above.
        heading("Fault ledger (injected chaos)")
        rows = []
        for i, sp in enumerate(faults):
            attrs = sp.get("attrs") or {}
            ctx = ", ".join(
                f"{k}={v}" for k, v in attrs.items() if k not in ("point", "kind")
            )
            rows.append(
                [str(i), str(attrs.get("point", "?")), str(attrs.get("kind", "?")),
                 ctx or "-"]
            )
        table(["#", "point", "kind", "context"], rows)

    points = [sp for sp in spans if sp["span"] == "sweep_point"]
    if points:
        heading("Sweep points")
        table(
            ["point", "runs", "elapsed"],
            [
                [str((sp.get("attrs") or {}).get("point", "?")),
                 str((sp.get("attrs") or {}).get("runs", "?")),
                 _fmt_s(float(sp.get("dur_s") or 0.0))]
                for sp in points
            ],
        )

    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# XLA trace-dir op attribution (absorbed from scripts/trace_report.py).


def find_trace_files(root: Path) -> list[Path]:
    return sorted(root.rglob("*.trace.json.gz")) + sorted(root.rglob("*.trace.json"))


def _load_trace_events(path: Path) -> list[dict]:
    opener = gzip.open if path.suffix == ".gz" else open
    with opener(path, "rt") as f:
        data = json.load(f)
    return data.get("traceEvents", data if isinstance(data, list) else [])


def trace_attribution(
    trace_dir: Path, top: int = 25, track_filter: str = ""
) -> str:
    """Total device time per op name for every chrome-trace file under
    ``trace_dir`` (the --trace-dir output), as a printable table."""
    files = find_trace_files(trace_dir)
    if not files:
        return f"no *.trace.json(.gz) under {trace_dir}\n"

    out: list[str] = []
    for path in files:
        events = _load_trace_events(path)
        proc_names: dict[int, str] = {}
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                proc_names[ev.get("pid")] = ev.get("args", {}).get("name", "")

        device_markers = ("TPU", "TensorCore", "Device", "/device:")
        has_device = any(
            any(m in name for m in device_markers) for name in proc_names.values()
        )
        wanted = track_filter or None

        totals: dict[tuple[str, str], float] = defaultdict(float)
        counts: dict[tuple[str, str], int] = defaultdict(int)
        for ev in events:
            if ev.get("ph") != "X":  # complete events carry durations
                continue
            name = proc_names.get(ev.get("pid"), "")
            if wanted is not None:
                if wanted not in name:
                    continue
            elif has_device and not any(m in name for m in device_markers):
                continue
            key = (name, ev.get("name", "?"))
            totals[key] += float(ev.get("dur", 0.0))
            counts[key] += 1

        grand = sum(totals.values())
        out.append(
            f"\n== {path.relative_to(trace_dir)}  "
            f"({len(events)} events, {grand / 1e3:.3f} ms summed on "
            f"{'filtered' if wanted else ('device' if has_device else 'all')} tracks)"
        )
        for (name, op), us in sorted(totals.items(), key=lambda kv: -kv[1])[:top]:
            pct = 100.0 * us / grand if grand else 0.0
            out.append(
                f"  {us / 1e3:10.3f} ms  {pct:5.1f}%  x{counts[(name, op)]:<6d} "
                f"{op}  [{name}]"
            )
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim report",
        description="Render a telemetry JSONL (or an XLA trace dir) into a dashboard.",
    )
    ap.add_argument("path", type=Path, help="telemetry .jsonl file, or a --trace-dir directory")
    ap.add_argument("--format", choices=("text", "md"), default="text")
    ap.add_argument("--out", type=Path, help="also write the rendered report here")
    ap.add_argument("--top", type=int, default=25, help="trace mode: ops to show")
    ap.add_argument(
        "--track-filter", default="",
        help="trace mode: only sum events whose track name contains this "
        "substring (default: prefer TPU/TensorCore tracks when present)",
    )
    ap.add_argument(
        "--slo-config", type=Path, metavar="FILE",
        help="render an SLO status panel from this JSON/TOML objectives "
        "config (same evaluator as `tpusim slo check`)",
    )
    ap.add_argument(
        "--lineage", type=Path, metavar="JSONL",
        help="render a provenance panel from this lineage ledger (default: "
        "every lineage.jsonl under a directory PATH)",
    )
    args = ap.parse_args(argv)

    slo = None
    if args.slo_config is not None:
        from .metrics import SloConfigError, load_objectives

        try:
            slo = load_objectives(args.slo_config)
        except SloConfigError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
    if not args.path.exists():
        print(f"error: {args.path} does not exist", file=sys.stderr)
        return 2
    # The provenance digest rides next to the span panels: an explicit
    # --lineage ledger, or every lineage.jsonl under a state-dir PATH
    # (tolerant load — a live writer may still be appending).
    from .provenance import load_lineage, summarize_lineage

    lineage_paths = (
        [args.lineage] if args.lineage is not None
        else sorted(args.path.rglob("lineage.jsonl")) if args.path.is_dir()
        else []
    )
    lineage_records: list[dict] = []
    for lp in lineage_paths:
        lineage_records.extend(load_lineage(lp))
    lineage = summarize_lineage(lineage_records)
    if args.path.is_dir():
        if find_trace_files(args.path):
            # XLA trace directory (--trace-dir output): op-level attribution.
            text = trace_attribution(
                args.path, top=args.top, track_filter=args.track_filter
            )
        else:
            # A fleet state dir (or any directory of telemetry ledgers):
            # merge every *.jsonl ledger under it — supervisor + workers —
            # deduplicated, and render ONE dashboard over the union; the
            # per-run panels partition by (run_id, process) so the shared
            # fleet run_id cannot blend worker streams.
            from .tracing import collect_spans

            spans = collect_spans([args.path])
            if not spans:
                print(
                    f"error: {args.path} holds neither XLA trace files nor "
                    f"telemetry ledgers", file=sys.stderr,
                )
                return 2
            text = render_report(
                spans, fmt=args.format, slo=slo, lineage=lineage
            )
    else:
        text = render_report(
            load_spans(args.path), fmt=args.format, slo=slo, lineage=lineage
        )
    try:
        print(text, end="", flush=True)
    except BrokenPipeError:
        pass  # `tpusim report ... | head` closing stdout early is not an error
    if args.out:
        args.out.parent.mkdir(parents=True, exist_ok=True)
        args.out.write_text(text)
    return 0


if __name__ == "__main__":
    sys.exit(main())
