"""Subprocess-based accelerator backend probe.

The tunneled TPU PJRT backend in this environment can wedge: ``jax.devices()``
then hangs for minutes inside the caller's own process, where no timeout can
rescue it (observed in rounds 3 and 4 — the BENCH_r03 failure and two lost
sweep launches). Probing from a *subprocess* is killable on timeout, and a
successful probe both proves and warms the tunnel for the in-process backend
init that follows.

Used by bench.py and the sweep CLI; safe to call before jax is imported in
the calling process (that is the point).
"""

from __future__ import annotations

import os
import subprocess
import sys
import time
from typing import Callable

from .chaos import ChaosError, InjectedHang

#: The env var this container's sitecustomize uses as the trigger to register
#: the tunneled TPU PJRT plugin at interpreter startup. probe_or_force_cpu
#: clears it so *child* processes skip the dead tunnel entirely; if the
#: sitecustomize trigger name ever changes, update it here.
TUNNEL_TRIGGER_ENV = "PALLAS_AXON_POOL_IPS"


def probe_backend(
    timeout_s: float = 150.0,
    retries: int = 3,
    backoff_s: float = 10.0,
    log: Callable[[str], None] | None = None,
    *,
    chaos=None,
    sleeper: Callable[[float], None] | None = None,
) -> str | None:
    """Return the platform name jax sees ("tpu", "cpu", ...) or None if the
    backend never comes up within ``retries`` subprocess probes.

    ``chaos`` (a tpusim.chaos.ChaosInjector) arms the ``probe.attempt``
    fault seam: an injected "hang" is reported exactly like a killed-on-
    timeout probe and a "transient" like a failing one — the dead-tunnel
    drill without a dead tunnel. ``sleeper`` overrides the inter-attempt
    ``time.sleep`` (tests inject a recorder instead of waiting)."""
    say = log or (lambda msg: print(f"[probe] {msg}", file=sys.stderr, flush=True))
    sleep = sleeper if sleeper is not None else time.sleep
    code = "import jax; print('PLATFORM=' + jax.devices()[0].platform)"
    for attempt in range(retries):
        r = None
        injected = False
        if chaos is not None:
            try:
                chaos.fire("probe.attempt", attempt=attempt)
            except InjectedHang:
                # The subprocess would have been killed at timeout_s; the
                # caller-visible outcome is identical.
                say(f"backend probe timed out after {timeout_s:.0f}s")
                injected = True
            except ChaosError as e:
                say(f"backend probe failed rc=-1: {e}")
                injected = True
        if not injected:
            try:
                r = subprocess.run(
                    [sys.executable, "-c", code],
                    capture_output=True, text=True, timeout=timeout_s,
                    env=os.environ.copy(),
                )
            except subprocess.TimeoutExpired:
                say(f"backend probe timed out after {timeout_s:.0f}s")
                r = None
            if r is not None:
                if r.returncode == 0:
                    for line in r.stdout.splitlines():
                        if line.startswith("PLATFORM="):
                            return line.split("=", 1)[1]
                tail = (r.stderr or "").strip().splitlines()[-1:] or ["?"]
                say(f"backend probe failed rc={r.returncode}: {tail[0][:200]}")
        if attempt + 1 < retries:
            pause = backoff_s * (attempt + 1)
            say(f"retrying backend probe in {pause:.0f}s ({attempt + 1}/{retries})")
            sleep(pause)
    return None


def probe_or_force_cpu(
    timeout_s: float = 150.0,
    retries: int = 3,
    backoff_s: float = 10.0,
    log: Callable[[str], None] | None = None,
    *,
    chaos=None,
    sleeper: Callable[[float], None] | None = None,
) -> str | None:
    """Probe the accelerator; on failure, force this process onto local CPU.

    Env vars alone are too late for the forcing: this container's
    sitecustomize registers the tunnel PJRT plugin at interpreter startup,
    so the first backend touch still goes to the dead tunnel and hangs in C
    land — where not even a SIGALRM watchdog fires. The fallback therefore
    clears the plugin trigger env (for child processes), sets JAX_PLATFORMS,
    and forces the platform through ``jax.config`` — valid any time before
    the first backend initialization, whether or not jax is imported yet.

    Returns the probed platform name, or None when CPU was forced. Callers:
    bench.py and __graft_entry__.entry (the sweep CLI instead fails loudly
    — a silent CPU sweep would waste hours).
    """
    platform = probe_backend(
        timeout_s, retries, backoff_s, log, chaos=chaos, sleeper=sleeper
    )
    if platform is None:
        force_cpu()
    return platform


def force_cpu() -> None:
    """Force this process onto local CPU, bypassing the tunnel plugin.

    Clears the plugin trigger env (for child processes), sets JAX_PLATFORMS,
    and forces the platform through ``jax.config`` — the config update is
    what actually works once sitecustomize has registered the plugin at
    interpreter startup; it is valid any time before the first backend
    initialization, whether or not jax is imported yet. Also used by
    scripts/mosaic_micro.py --allow-cpu."""
    os.environ.pop(TUNNEL_TRIGGER_ENV, None)
    os.environ["JAX_PLATFORMS"] = "cpu"
    import jax

    jax.config.update("jax_platforms", "cpu")
