"""``tpusim watch`` — live terminal dashboard over a telemetry JSONL ledger.

The ``tpusim report`` dashboard is a post-mortem; this is the during-mortem
twin: point it at the ledger a running ``--telemetry`` simulation (or sweep)
is appending to, and it re-renders throughput, per-statistic CI narrowing
(the ``stats`` spans of tpusim.convergence), occupancy and the fault ledger
every few seconds until the run's closing span lands.

    python -m tpusim watch artifacts/telemetry/run.jsonl            # live
    python -m tpusim watch --once artifacts/telemetry/run.jsonl     # snapshot

Deliberately jax-free: it imports no backend, so it starts instantly on the
same (busy) host, inside a dying SSH window, or in CI — ``--once`` renders
one snapshot and exits, which is the dead-terminal and smoke-test mode
(scripts/ci.sh). Reading is crash-tolerant by construction: it re-parses the
whole ledger each refresh through ``telemetry.load_spans``, which skips the
torn line a concurrently-writing run may have in flight.
"""

from __future__ import annotations

import argparse
import sys
import time
from pathlib import Path

from .convergence import format_num, point_snapshot_rows, snapshot_rows
from .report import format_bytes, text_table
from .telemetry import BatchRecord, load_spans, throughput_report

__all__ = ["render_watch", "main"]

#: ANSI clear-screen + home: the live loop repaints in place.
_CLEAR = "\x1b[2J\x1b[H"


def _bar(frac: float, width: int = 28) -> str:
    frac = min(max(frac, 0.0), 1.0)
    n = round(width * frac)
    return "[" + "#" * n + "." * (width - n) + "]"


def render_watch(
    spans: list[dict], source: str, now: float | None = None, slo=None,
    lineage=None,
) -> str:
    """One full dashboard frame for the ledger's CURRENT state. Ledgers can
    hold several runs (appended files, sweeps): panels follow the most
    recent ``run_id``, and the header says how many others there are.

    ``slo`` is an optional list of :class:`tpusim.metrics.Objective`; when
    given, an SLO status panel re-evaluates every frame through the SAME
    shared evaluator ``tpusim slo check`` gates on (span-scoped here:
    perf-ledger objectives show NO-DATA in a live frame)."""
    if now is None:
        now = time.time()
    out: list[str] = [f"tpusim watch — {source}"]
    if not spans:
        out.append("  (no parseable spans yet — waiting for the run to emit)")
        return "\n".join(out) + "\n"

    # str-normalized like the report's grouping: "run_id": null must neither
    # crash the set count nor split the panels from their own run.
    run_ids = [str(sp.get("run_id") or "?") for sp in spans]
    rid = run_ids[-1]
    mine = [sp for sp in spans if str(sp.get("run_id") or "?") == rid]
    n_other = len(set(run_ids)) - 1
    last_t = max((sp.get("t_start") or 0.0) + (sp.get("dur_s") or 0.0) for sp in mine)
    completed = any(sp["span"] == "run" for sp in mine)
    head = (
        f"run_id {rid}"
        + (f" (+{n_other} earlier in this ledger)" if n_other else "")
        + f" · {len(mine)} spans · last span {max(now - last_t, 0.0):.1f} s ago"
        + f" · {'COMPLETED' if completed else 'RUNNING'}"
    )
    out.append(head)

    batches = [sp for sp in mine if sp["span"] == "batch"]
    sstats = [sp for sp in mine if sp["span"] == "stats"]
    last_stats = (sstats[-1].get("attrs") or {}) if sstats else {}

    # --- Progress + throughput. runs_done is the RUN-level cumulative
    # (checkpoint-resumed base included); `runs` is the session-scoped
    # accumulator count and would understate a resumed run's progress.
    runs_done = last_stats.get("runs_done", last_stats.get("runs"))
    runs_total = last_stats.get("runs_total")
    if runs_done is None and batches:
        runs_done = sum(int((sp.get("attrs") or {}).get("runs") or 0) for sp in batches)
    if runs_done is not None:
        line = f"runs {runs_done}"
        if runs_total:
            line += (
                f"/{runs_total} ({100.0 * runs_done / runs_total:.1f}%)  "
                + _bar(runs_done / runs_total)
            )
        out.append(line)
    if batches:
        records = [
            BatchRecord(
                int((sp.get("attrs") or {}).get("runs") or 0),
                float(sp.get("dur_s") or 0.0),
            )
            for sp in batches
        ]
        # duration_ms rides every stats span, so sim-rate is derivable
        # mid-run; a foreign ledger without one still gets run-rate.
        if last_stats.get("duration_ms") is not None:
            rep = throughput_report(
                records, int(last_stats.get("duration_ms") or 0),
                float(last_stats.get("block_interval_s") or 600.0),
            )
        else:
            rep = throughput_report(records, 0, 600.0)
            rep.pop("steady_sim_years_per_s", None)
            rep.pop("steady_events_per_s", None)
        line = (
            f"throughput {rep['steady_runs_per_s']} runs/s"
            + (
                f" · {rep['steady_sim_years_per_s']} sim-years/s"
                if "steady_sim_years_per_s" in rep else ""
            )
            + f" · {rep['batches']} batch(es), first {rep['first_batch_s']} s (compile)"
        )
        if rep.get("steady_is_first_batch"):
            # The steady_is_first_batch discipline: never pass the compile
            # batch off as steady state without saying so.
            line += " · SINGLE BATCH — compile-contaminated estimate"
        out.append(line)
        active = sum(int((sp.get("attrs") or {}).get("active_steps") or 0) for sp in batches)
        slots = sum(int((sp.get("attrs") or {}).get("step_slots") or 0) for sp in batches)
        retries = sum(int((sp.get("attrs") or {}).get("retries") or 0) for sp in batches)
        occ = f"{active / slots:.3f}" if slots else "n/a"
        out.append(f"occupancy {occ} · retries {retries}")

    # --- Compile & memory (the perf-observability spans/attrs). Live view
    # of what `tpusim report` renders as full panels: a recompiling sweep or
    # a climbing live-buffer watermark should be visible while it happens.
    compiles = [sp for sp in mine if sp["span"] == "compile"]
    cache_sp = [sp for sp in mine if sp["span"] == "engine_cache"]
    mem = [
        sp.get("attrs") or {}
        for sp in mine
        if sp["span"] == "batch" and "mem_live_bytes" in (sp.get("attrs") or {})
    ]
    if compiles or cache_sp or mem:
        parts = []
        if compiles:
            total = sum(float(sp.get("dur_s") or 0.0) for sp in compiles)
            parts.append(f"compiles {len(compiles)} ({total:.2f} s)")
        if cache_sp:
            hits = sum(1 for sp in cache_sp if (sp.get("attrs") or {}).get("hit"))
            parts.append(f"engine cache {hits}/{len(cache_sp)} hit")
        if mem:
            watermark = max(a.get("mem_live_bytes") or 0 for a in mem)
            parts.append(f"live buffers {format_bytes(watermark)}")
            last = mem[-1]
            est, budget = last.get("vmem_est_bytes"), last.get("vmem_budget_bytes")
            if est is not None and budget:
                parts.append(f"VMEM est {100 * est / budget:.0f}% of budget")
        out.append(" · ".join(parts))

    # --- Fleet supervisor (tpusim.fleet): the elastic-sweep live state —
    # workers alive, leases and their beat progress, requeues, quarantines.
    # Same summarizer as the report panel, so the surfaces cannot drift.
    from .fleet import summarize_fleet_spans

    fleet = summarize_fleet_spans(mine)
    if fleet is not None:
        def orq(v):  # a foreign/partial status renders "?", never a crash
            return "?" if v is None else v

        line = (
            f"fleet: {orq(fleet['workers_alive'])} worker(s) alive · "
            f"{orq(fleet['points_done'])}/{orq(fleet['points_total'])} points"
            f" · {orq(fleet['queued'])} queued · {len(fleet['requeues'])} requeue(s)"
        )
        if fleet["quarantined"]:
            line += f" · QUARANTINED: {', '.join(fleet['quarantined'])}"
        out.append(line)
        if fleet["leases"]:
            parts = []
            for entry in fleet["leases"]:
                lease = f"{entry.get('point', '?')}->{entry.get('worker', '?')}"
                if entry.get("runs_done") is not None:
                    lease += f" ({entry['runs_done']}/{entry.get('runs_total', '?')})"
                parts.append(lease)
            out.append("  leases: " + ", ".join(parts))
        # Per-worker occupancy, live: lease wall-clock per worker as a share
        # of the fleet window so far (the supervisor ledger alone carries no
        # worker spans — the category breakdown lives in `tpusim report
        # STATE_DIR` and `tpusim trace timeline`, which merge them).
        from .tracing import assemble, worker_utilization

        trace = assemble(mine)
        if trace is not None and trace.workers:
            window = max(trace.t1 - trace.t0, 1e-9)
            parts = []
            for r in worker_utilization(trace)[-6:]:
                share = min(r["alive_s"] / window, 1.0)
                parts.append(
                    f"{r['worker']} {r['point']} {r['alive_s']:.1f}s"
                    f" ({100.0 * share:.0f}%, {r['end_reason']})"
                )
            out.append("  worker leases (share of fleet window): " + ", ".join(parts))

    # --- Convergence (the stats spans this dashboard exists for).
    out.append("")
    prows = point_snapshot_rows(sstats)
    # A MIXED packed sweep carries both span kinds: per-point segments from
    # the packed dispatches and plain spans from unpackable fallback points
    # (xoroshiro/flight) that ran through the runner. Each renders from its
    # own subset so no point's narrowing disappears.
    blended = [
        s for s in sstats
        if not isinstance((s.get("attrs") or {}).get("point"), str)
    ]
    last_stats = (blended[-1].get("attrs") or {}) if blended else last_stats
    if prows is not None:
        # Packed sweep (tpusim.packed): the spans are per-POINT segments —
        # render each grid point's own progress and CI narrowing instead of
        # one blended run. Same shared extraction as the report panel.
        target = last_stats.get("target_rel_hw")
        title = "convergence by grid point (packed sweep"
        if target is not None:
            title += f", target rel hw {format_num(target)}"
        out.append(title + "):")
        out.extend(
            text_table(["point", "runs", "rel hw95 (worst stat)", "status"], prows)
        )
    if blended:
        sstats = blended
        target = last_stats.get("target_rel_hw")
        title = f"convergence (95% CI, n={last_stats.get('runs', '?')}"
        if target is not None:
            title += f", target rel hw {format_num(target)}"
        if last_stats.get("rate_is_first_batch"):
            title += ", rate from first batch — compile-contaminated"
        out.append(title + "):")
        per_stat: dict = last_stats.get("stats") or {}
        rows = snapshot_rows(per_stat)
        out.extend(text_table(["stat", "rel hw (worst miner)", "hw95 (max)", "eta to target"], rows))
        # Narrowing trend: first -> latest worst relative half-width. A
        # growing n with a shrinking rel hw is the 1/sqrt(n) signature;
        # anything else is worth staring at.
        trends = []
        first_stats = (sstats[0].get("attrs") or {}).get("stats") or {}
        for stat, entry in per_stat.items():
            first = first_stats.get(stat)
            if not isinstance(entry, dict) or not isinstance(first, dict):
                continue
            a = first.get("rel_hw_max")
            b = entry.get("rel_hw_max")
            # isinstance, not truthiness: a foreign ledger's string value
            # must render as "no trend", not crash the frame.
            if isinstance(a, (int, float)) and isinstance(b, (int, float)) and a and b > 0:
                trends.append(f"{stat} x{a / b:.2f}")
        if len(sstats) > 1 and trends:
            out.append(
                f"  narrowing over {len(sstats)} batches: " + ", ".join(trends)
            )
    elif prows is None:
        out.append("convergence: no stats spans yet (run with --telemetry on a "
                   "tpusim version that emits them)")

    # --- SLO status (tpusim.metrics): the declarative objectives, evaluated
    # live over the frame's spans by the same evaluator `slo check` exits
    # from — a violation shows here the refresh it happens.
    if slo:
        from .metrics import SLO_HEADERS, evaluate_slos, slo_rows, snapshot_from_spans

        results = evaluate_slos(slo, snapshot_from_spans(spans, now=now))
        worst = ("violation" if any(r["status"] == "violation" for r in results)
                 else "no-data" if any(r["status"] == "no-data" for r in results)
                 else "pass")
        out.append(f"SLO status ({worst.upper()}):")
        out.extend(text_table(SLO_HEADERS, slo_rows(results)))

    # --- Provenance digest (tpusim.provenance): the lineage ledger growing
    # next to this span ledger, re-read every frame through the tolerant
    # loader — same digest as the `tpusim report` panel.
    if lineage:
        kinds = ", ".join(
            f"{k}:{n}" for k, n in sorted(lineage["kinds"].items())
        )
        out.append(
            f"provenance: {lineage['records']} lineage record(s) · "
            f"{lineage['edges']} parent edge(s) · {kinds}"
        )

    # --- Fault ledger.
    faults = [sp for sp in mine if sp["span"] == "chaos"]
    if faults:
        last = faults[-1].get("attrs") or {}
        out.append(
            f"fault ledger: {len(faults)} injected fault(s), last "
            f"{last.get('point', '?')}/{last.get('kind', '?')}"
        )
    return "\n".join(out) + "\n"


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim watch",
        description="Live terminal dashboard over a --telemetry JSONL ledger.",
    )
    ap.add_argument("path", type=Path, help="telemetry .jsonl ledger to tail")
    ap.add_argument(
        "--once", action="store_true",
        help="render one snapshot and exit (CI / dead-terminal mode)",
    )
    ap.add_argument(
        "--interval", type=float, default=2.0, metavar="S",
        help="refresh period in seconds (default 2.0)",
    )
    ap.add_argument(
        "--follow", action="store_true",
        help="keep watching after the run's closing span (default: exit then)",
    )
    ap.add_argument(
        "--no-clear", action="store_true",
        help="append frames instead of repainting (dumb terminals / logs)",
    )
    ap.add_argument(
        "--wait-for-file", type=float, default=0.0, metavar="S",
        help="poll up to S seconds for the ledger file to appear before "
        "rendering (bounded) — lets a fleet drill or CI start the watcher "
        "BEFORE the supervisor/run creates the ledger; --once still exits "
        "rc 2 if the file never appears within the bound",
    )
    ap.add_argument(
        "--slo-config", type=Path, metavar="FILE",
        help="re-evaluate this JSON/TOML objectives config every frame and "
        "render an SLO status panel (same evaluator as `tpusim slo check`)",
    )
    ap.add_argument(
        "--lineage", type=Path, metavar="JSONL",
        help="re-read this lineage ledger every frame and render a "
        "provenance line (default: $TPUSIM_PROVENANCE when set)",
    )
    args = ap.parse_args(argv)

    slo = None
    if args.slo_config is not None:
        from .metrics import SloConfigError, load_objectives

        try:
            slo = load_objectives(args.slo_config)
        except SloConfigError as e:
            print(f"error: {e}", file=sys.stderr)
            return 2

    if args.wait_for_file > 0 and not args.path.exists():
        deadline = time.monotonic() + args.wait_for_file
        while not args.path.exists() and time.monotonic() < deadline:
            time.sleep(min(0.2, args.wait_for_file))
    if args.once and not args.path.exists():
        print(f"error: {args.path} does not exist", file=sys.stderr)
        return 2
    import os

    from .provenance import PROVENANCE_ENV, load_lineage, summarize_lineage

    lineage_path = args.lineage
    if lineage_path is None and os.environ.get(PROVENANCE_ENV):
        lineage_path = Path(os.environ[PROVENANCE_ENV])
    try:
        while True:
            spans = load_spans(args.path) if args.path.exists() else []
            lineage = (
                summarize_lineage(load_lineage(lineage_path))
                if lineage_path is not None else None
            )
            frame = render_watch(spans, str(args.path), slo=slo, lineage=lineage)
            if not args.once and not args.no_clear:
                sys.stdout.write(_CLEAR)
            try:
                print(frame, end="", flush=True)
            except BrokenPipeError:
                return 0  # `tpusim watch --once | head` is not an error
            if args.once:
                return 0
            if spans and not args.follow:
                # Exit when the run the panels follow (the ledger's newest
                # run_id — an appended file may hold earlier completed runs)
                # has emitted its closing span; the final frame is already
                # on screen.
                rid = spans[-1].get("run_id", "?")
                if any(
                    sp.get("span") == "run" and sp.get("run_id", "?") == rid
                    for sp in spans
                ):
                    return 0
            time.sleep(args.interval)
    except KeyboardInterrupt:
        print()
        return 0


if __name__ == "__main__":
    sys.exit(main())
