"""Unified telemetry: run-scoped structured spans plus the shared metrics
registry.

The reference's only observability is a stdout progress percentage
(main.cpp:219). Operating unattended production sweeps (ROADMAP north star)
needs two correlated layers instead, and this module is the single host-side
sink for both:

  * **Structured spans** — every batch, sweep point, checkpoint save/load,
    retry, pipelined-dispatch stall and per-batch convergence snapshot
    (the ``stats`` spans of tpusim.convergence) is one JSONL line
    ``{"run_id", "span", "t_start", "t_mono", "dur_s", "schema",
    "process", "trace_id", ["parent_span",] "attrs"}`` written by
    :class:`TelemetryRecorder`. One ``run_id`` correlates every span of a
    run (and every point of a sweep), so a ledger can be grepped, joined
    across processes, or rendered into the ``tpusim report`` dashboard
    (tpusim.report). ``t_start`` is wall-clock epoch seconds (cross-process
    correlation); ``dur_s`` comes from the monotonic clock; ``t_mono`` is
    the raw monotonic reading at write time (span END), which is what the
    distributed-tracing merger (tpusim.tracing) rebases per process so a
    stepped wall clock can never reorder a timeline. ``schema`` is
    :data:`SCHEMA_VERSION` (spans without one — pre-tracing ledgers — load
    fine everywhere: every consumer treats the new fields as optional);
    ``process`` identifies the emitting process; ``trace_id`` /
    ``parent_span`` are the cross-process correlation pair propagated to
    fleet workers via :data:`tpusim.tracing.TRACE_ENV` (``trace_id``
    defaults to the recorder's own ``run_id`` at the trace root).
  * **Metrics registry** — :class:`MetricsRegistry` accumulates per-batch
    timing records and derives the phase/throughput report.
    ``tpusim.profiling.Profiler`` is a thin client of it, and
    :func:`throughput_report` is the one implementation of the steady-state
    throughput math, shared by ``Profiler.report`` and the ``tpusim report``
    dashboard — bench numbers and telemetry can never disagree about what
    "steady-state sim-years/sec" means.

Device-side counterpart: the engines accumulate per-run simulation counters
(max reorg depth, stale-event count, active steps) in the carried aux tree at
near-zero cost (tpusim.engine.SimCounters); the runner folds their per-batch
reductions into each ``batch`` span's attrs, which is how sim-domain telemetry
reaches this sink without an extra device round trip.

Recorder writes are append-only, line-buffered, and crash-tolerant to read
back: :func:`load_spans` skips truncated or foreign lines the same way the
sweep ``--resume`` scanner does.
"""

from __future__ import annotations

import contextlib
import dataclasses
import json
import logging
import os
import time
import uuid
from pathlib import Path
from typing import Any, Iterator

logger = logging.getLogger("tpusim")

#: Span-row schema version. v2 added t_mono/schema/process/trace_id/
#: parent_span (all additive); v1 ledgers carry none of them and every
#: consumer tolerates their absence.
SCHEMA_VERSION = 2

#: This process's span identity: stable across every recorder the process
#: creates (a fleet worker's handshake recorder and its runner recorder
#: must land in ONE trace process), but unique beyond the pid — a year-long
#: elastic fleet spawns enough workers that the kernel recycles pids, and
#: two attempts sharing a bare pid would merge into one timeline process.
PROCESS_ID = f"p{os.getpid()}-{uuid.uuid4().hex[:4]}"

__all__ = [
    "SCHEMA_VERSION",
    "TelemetryRecorder",
    "MetricsRegistry",
    "BatchRecord",
    "CompileLedger",
    "throughput_report",
    "append_jsonl_line",
    "load_spans",
    "new_run_id",
    "environment_attrs",
    "device_memory_attrs",
]


def append_jsonl_line(path: str | Path, line: str, *, fsync: bool = False) -> None:
    """Append one line to an append-only JSONL file, repairing a torn trailing
    line first: a killed window (``timeout -k`` mid-write, a preempted VM) can
    leave the file's final line truncated with no newline, and appending
    directly would glue the new row onto the fragment and make both
    unparseable. The trailing byte is probed/repaired through a separate
    BINARY handle: text-mode ``tell()`` returns an opaque cookie on which
    arithmetic is undefined (io docs) and could mis-seek if a row ever
    contains non-ASCII. THE shared append discipline behind the sweep row
    writer (tpusim.sweep) and the fleet supervisor's work ledger
    (tpusim.fleet) — crash tolerance on the write side, pairing
    :func:`load_spans`-style tolerance on the read side.

    ``fsync=True`` flushes and fsyncs the append before returning: once the
    call returns, the line survives a SIGKILL/power cut. Ledgers whose rows
    are *evidence* rather than observability (the provenance lineage ledger,
    the fleet work ledger) pay the sync; high-rate span streams do not."""
    path = Path(path)
    if path.exists() and path.stat().st_size > 0:
        with path.open("rb+") as bh:
            bh.seek(-1, 2)
            if bh.read(1) != b"\n":
                bh.write(b"\n")
    with path.open("a") as fh:
        fh.write(line.rstrip("\n") + "\n")
        if fsync:
            fh.flush()
            os.fsync(fh.fileno())


def environment_attrs() -> dict[str, Any]:
    """Environment identity for the closing ``run`` span: jax version, device
    kind/count, and the tpusim version — so benchmark JSONLs gathered from
    different hosts are self-describing instead of relying on the ROADMAP's
    prose drift notes. Never raises: telemetry must not take a run down, so
    lookup failures degrade to whatever fields resolved."""
    attrs: dict[str, Any] = {}
    try:
        from . import __version__

        attrs["tpusim_version"] = __version__
    except Exception:  # pragma: no cover - import cycle / stripped package
        pass
    try:
        import jax

        attrs["jax_version"] = jax.__version__
        devices = jax.devices()
        attrs["device_count"] = len(devices)
        attrs["device_kind"] = devices[0].device_kind
        attrs["platform"] = devices[0].platform
    except Exception:  # pragma: no cover - uninitializable backend
        pass
    return attrs


def device_memory_attrs() -> dict[str, Any]:
    """Per-batch device-memory observability, best effort and never raising
    (same contract as :func:`environment_attrs`):

      * ``mem_live_buffers`` / ``mem_live_bytes`` — count and byte total of
        every live jax array in the process (``jax.live_arrays``), the
        cross-platform live-buffer watermark;
      * ``mem_bytes_in_use`` / ``mem_peak_bytes`` — the backend allocator's
        own counters where the platform exposes ``memory_stats()`` (TPU/GPU;
        the CPU backend reports none and the keys are simply absent).

    Called once per batch span by the runner — a host-side walk of the live
    array registry, nowhere near the dispatch hot path.
    """
    attrs: dict[str, Any] = {}
    try:
        import jax

        live = jax.live_arrays()
        attrs["mem_live_buffers"] = len(live)
        attrs["mem_live_bytes"] = int(sum(getattr(a, "nbytes", 0) for a in live))
        stats = jax.local_devices()[0].memory_stats()
        if stats:
            if "bytes_in_use" in stats:
                attrs["mem_bytes_in_use"] = int(stats["bytes_in_use"])
            if "peak_bytes_in_use" in stats:
                attrs["mem_peak_bytes"] = int(stats["peak_bytes_in_use"])
    except Exception:  # pragma: no cover - backend without the introspection
        pass
    return attrs


class CompileLedger:
    """Session-scoped XLA compile observability: one ``compile`` telemetry
    span per backend compile, plus engine-cache hit/miss counters.

    The assertion half of the compile story is
    :func:`tpusim.testing.compile_count_guard` (tests pin "this block
    compiles exactly N times"); this is the observability half — production
    runs RECORD every compile with its duration and whatever context the
    orchestration layer has set (which engine, which dispatch path, which
    ``Engine.reuse_key``), so a recompile regression shows up in the ledger
    of the run that paid for it instead of only in a test somebody runs.

    Purely host-side by construction: it subscribes to the same
    ``jax.monitoring`` duration-event listener the guard uses, so the chunk
    programs are untouched (jaxpr byte-identical with a ledger armed —
    pinned by tests/test_perf_obs.py). ``install``/``uninstall`` bound the
    subscription to one run; the runner arms it whenever ``--telemetry`` is
    on.
    """

    def __init__(self, recorder: "TelemetryRecorder | None" = None):
        self.recorder = recorder
        self.compiles = 0
        self.compile_s = 0.0
        self.cache_hits = 0
        self.cache_misses = 0
        self._context: dict[str, Any] = {}
        self._unsubscribe = None

    def install(self) -> "CompileLedger":
        if self._unsubscribe is None:
            from .testing import subscribe_backend_compiles

            self._unsubscribe = subscribe_backend_compiles(self._on_compile)
        return self

    def uninstall(self) -> None:
        if self._unsubscribe is not None:
            self._unsubscribe()
            self._unsubscribe = None

    def set_context(self, **attrs: Any) -> None:
        """Merge orchestration context into every subsequent compile span —
        the listener only sees (event name, duration), so the dispatch path
        and engine identity must be narrated by whoever is dispatching."""
        self._context.update(attrs)

    def _on_compile(self, name: str, secs: float) -> None:
        self.compiles += 1
        self.compile_s += float(secs)
        if self.recorder is not None:
            self.recorder.emit(
                "compile", t_start=time.time() - float(secs),
                dur_s=float(secs), event=name, **self._context,
            )

    def cache_event(self, hit: bool, key: Any = None) -> None:
        """One engine-cache lookup (tpusim.runner.make_engine): a hit rebinds
        a warm compiled engine, a miss pays construction + first-dispatch
        compilation. Emitted as an ``engine_cache`` span so sweeps show their
        reuse discipline in the same ledger as the compiles it avoids."""
        if hit:
            self.cache_hits += 1
        else:
            self.cache_misses += 1
        if self.recorder is not None:
            self.recorder.emit("engine_cache", hit=bool(hit), key=repr(key))

    def summary_attrs(self) -> dict[str, Any]:
        """Run-level totals for the closing ``run`` span."""
        return {
            "compiles": self.compiles,
            "compile_span_s": round(self.compile_s, 4),
            "engine_cache_hits": self.cache_hits,
            "engine_cache_misses": self.cache_misses,
        }


def new_run_id() -> str:
    """A fresh correlating id: short enough to grep, unique enough to join
    telemetry from many hosts into one ledger."""
    return uuid.uuid4().hex[:12]


def _jsonable(value: Any) -> Any:
    """Coerce numpy scalars/arrays (the usual attr payload from engine sums)
    into plain JSON types; reject nothing — telemetry must never throw in the
    hot loop, so unknown objects degrade to their repr."""
    if isinstance(value, (str, int, float, bool)) or value is None:
        return value
    if isinstance(value, dict):
        return {str(k): _jsonable(v) for k, v in value.items()}
    if isinstance(value, (list, tuple)):
        return [_jsonable(v) for v in value]
    item = getattr(value, "item", None)
    if callable(item):
        try:
            return _jsonable(item())
        except (TypeError, ValueError):
            pass
    tolist = getattr(value, "tolist", None)
    if callable(tolist):
        try:
            return _jsonable(tolist())
        except (TypeError, ValueError):
            pass
    return repr(value)


class TelemetryRecorder:
    """Run-scoped JSONL span sink.

    One recorder per logical run (or sweep); every emitted line carries its
    ``run_id``. The file handle is opened lazily and line-buffered so a
    killed process loses at most the line being written — which
    :func:`load_spans` tolerates on read-back.

    Writes are best-effort by contract: a failed write (ENOSPC, a yanked
    volume) warns once and disables the recorder for the rest of the run —
    telemetry must never take a run down. ``chaos`` (tpusim.chaos) is the
    fault-injection seam that drills exactly that path.

    **Trace context** (tpusim.tracing): a recorder created inside a fleet
    worker finds ``TPUSIM_TRACE_CONTEXT`` in its environment and adopts the
    supervisor's ``trace_id``/``run_id`` plus the ``parent_span`` naming the
    spawn that created it — so every span this process ever emits lands in
    the supervisor's span tree with no caller plumbing. At the trace root
    (no context) ``trace_id`` defaults to the recorder's own ``run_id``.
    An explicit ``run_id`` argument always wins over the context's.
    """

    def __init__(
        self, path: str | Path, run_id: str | None = None, chaos=None,
        trace=None,
    ):
        from .tracing import TraceContext  # lazy: tracing imports load_spans

        ctx = trace if trace is not None else TraceContext.from_env()
        self.path = Path(path)
        self.run_id = run_id or (ctx.run_id if ctx else None) or new_run_id()
        self.trace_id = ctx.trace_id if ctx else self.run_id
        self.parent_span = ctx.parent_span if ctx else None
        self.process = PROCESS_ID
        self.chaos = chaos
        self._fh = None
        self._dead = False

    def emit(
        self,
        span: str,
        *,
        t_start: float | None = None,
        dur_s: float = 0.0,
        **attrs: Any,
    ) -> None:
        """Append one span line. ``t_start`` defaults to now (an
        instantaneous event); externally-timed spans pass their own."""
        if self._dead:
            return
        row = {
            "run_id": self.run_id,
            "span": span,
            "t_start": round(time.time() if t_start is None else t_start, 6),
            # Monotonic reading at WRITE time == the span's END on a clock
            # that cannot step; backdated t_start emissions included, since
            # end - dur_s recovers the start (tpusim.tracing rebases on it).
            "t_mono": round(time.monotonic(), 6),
            "dur_s": round(float(dur_s), 6),
            "schema": SCHEMA_VERSION,
            "process": self.process,
            "trace_id": self.trace_id,
            **({"parent_span": self.parent_span}
               if self.parent_span is not None else {}),
            "attrs": _jsonable(attrs),
        }
        try:
            if self.chaos is not None and span != "chaos":
                # "chaos" spans are the injector's own ledger lines; letting
                # a telemetry.write fault fire while recording one would
                # recurse into a second injection. ("target", not "span": the
                # injector reports context through emit(span="chaos", ...).)
                self.chaos.fire("telemetry.write", target=span)
            if self._fh is None:
                self.path.parent.mkdir(parents=True, exist_ok=True)
                self._fh = self.path.open("a", buffering=1)
            self._fh.write(json.dumps(row) + "\n")
        except OSError as e:
            self._dead = True
            logger.warning(
                "telemetry write to %s failed (%s); disabling the recorder "
                "for the rest of this run", self.path, e,
            )
            if self._fh is not None:
                try:
                    self._fh.close()
                except OSError:
                    pass
                self._fh = None

    @contextlib.contextmanager
    def span(self, name: str, **attrs: Any) -> Iterator[dict[str, Any]]:
        """Time a block as one span; the yielded dict lets the body add
        result attrs before the line is written."""
        t0_wall = time.time()
        t0 = time.perf_counter()
        extra: dict[str, Any] = dict(attrs)
        try:
            yield extra
        finally:
            self.emit(name, t_start=t0_wall, dur_s=time.perf_counter() - t0, **extra)

    def close(self) -> None:
        if self._fh is not None:
            self._fh.close()
            self._fh = None


def load_spans(path: str | Path) -> list[dict]:
    """Read a telemetry JSONL back, skipping truncated/foreign lines (a
    killed window can cut the final line mid-write, exactly like the sweep
    output files — same tolerance policy as the ``--resume`` scanner).
    ``errors="replace"``: a line torn inside a multi-byte sequence must not
    turn into a decode exception that hides every intact span before it."""
    spans = []
    for line in Path(path).read_text(errors="replace").splitlines():
        if not line.strip():
            continue
        try:
            row = json.loads(line)
        except json.JSONDecodeError:
            continue
        # A string span name is the one row field every consumer keys on;
        # a foreign row carrying "span": null (or a number) is not a span
        # and would crash the dashboards' grouping, so it is filtered here
        # like any other non-span line.
        if isinstance(row, dict) and isinstance(row.get("span"), str):
            spans.append(row)
    return spans


@dataclasses.dataclass
class BatchRecord:
    runs: int
    elapsed_s: float


def throughput_report(
    records: list[BatchRecord], duration_ms: int, block_interval_s: float
) -> dict[str, Any]:
    """Phase timings + throughput from per-batch wall times — THE shared
    derivation behind ``Profiler.report`` and the ``tpusim report``
    dashboard. The first batch carries the jit compilation (compile + first
    execution; JAX does not expose the split without a trace); steady-state
    numbers use the remaining batches when there are any, and otherwise
    reuse batch 0 with ``steady_is_first_batch=True`` — a single-batch run
    has only compile-contaminated numbers and must say so instead of
    passing them off as steady state."""
    if not records:
        return {"batches": 0}
    total_runs = sum(r.runs for r in records)
    total_s = sum(r.elapsed_s for r in records)
    steady = records[1:] or records
    steady_is_first_batch = not records[1:]
    steady_runs = sum(r.runs for r in steady)
    steady_s = sum(r.elapsed_s for r in steady) or 1e-12
    years_per_run = duration_ms / (365.2425 * 86_400_000.0)
    events_per_run = 2.0 * duration_ms / (block_interval_s * 1000.0)
    return {
        "batches": len(records),
        "total_runs": total_runs,
        "total_s": round(total_s, 4),
        "first_batch_s": round(records[0].elapsed_s, 4),
        "steady_is_first_batch": steady_is_first_batch,
        "steady_runs_per_s": round(steady_runs / steady_s, 3),
        "steady_sim_years_per_s": round(steady_runs * years_per_run / steady_s, 3),
        "steady_events_per_s": round(steady_runs * events_per_run / steady_s, 1),
    }


@dataclasses.dataclass
class MetricsRegistry:
    """The shared sink for host-side batch timing. ``Profiler`` delegates
    storage and report derivation here; anything else that times batches
    (bench loops, ad-hoc harnesses) can feed the same registry and get the
    same report."""

    batches: list[BatchRecord] = dataclasses.field(default_factory=list)

    def record_batch(self, runs: int, elapsed_s: float) -> None:
        self.batches.append(BatchRecord(runs, elapsed_s))

    def throughput(self, duration_ms: int, block_interval_s: float) -> dict[str, Any]:
        return throughput_report(self.batches, duration_ms, block_interval_s)
