"""Device-side event flight recorder: a fixed-capacity ring buffer of packed
per-event rows, carried with the simulation state.

The PR-2 counters (:class:`tpusim.engine.SimCounters`) are scalar reductions —
when a sweep point disagrees with the native C++ reference they say *how much*
diverged, never *which events*. The flight recorder closes that gap: with
``SimConfig.flight_capacity > 0`` every simulation event writes one packed
int32 row into a per-run ring buffer that rides the same HBM round trip as the
state tree (a :class:`FlightRecorder` leaf in the scan engine's carried aux,
three extra VMEM-resident leaves in the Pallas kernel), and the host decodes
it into a Chrome-trace/Perfetto timeline or a JSONL event log
(:mod:`tpusim.flight_export`). With the default ``flight_capacity = 0`` the
recorder does not exist: no leaves are created, no ops are traced, the jitted
programs are byte-identical to a recorder-less build (pinned by
tests/test_flight.py).

Row layout (``N_FIELDS`` int32 words): ``kind, miner, height, depth, t_hi,
t_lo``. Event time is absolute simulation milliseconds as a base-2^30 int32
limb pair (``t_hi * 2^30 + t_lo``; the engine re-bases every run's int32 clock
per chunk, so the recorder carries each run's absolute chunk origin in the
same limb form and the host reassembles int64 times at decode).

Event kinds, classified exactly like the reference event loop
(main.cpp:128-192) iterations:

  * ``find``    — a block find was due this step; ``miner`` is the winner,
    ``height`` its chain length including the new block (private included).
  * ``arrival`` — no find was due and the notify sweep flushed >= 1 pending
    propagation group; ``miner`` owns the earliest flushed arrival (lowest
    index on ties), ``height`` is that miner's post-sweep chain length. A
    flush folded into a same-millisecond find step records as the find alone,
    matching the reference's single loop iteration for that instant.
  * ``stale`` / ``reorg`` — the sweep made >= 1 miner adopt the best chain;
    ``stale`` when the adoptions popped own blocks (``depth`` = the max pops
    by a single adopter — the same quantity SimCounters.reorg_max tracks),
    plain ``reorg`` when no block was lost. ``miner`` is the adopter with the
    deepest pop (lowest index on ties), ``height`` the adopted best height.

A step can record two rows (its find-or-arrival row, then its adoption row),
so trace-event counts tie out exactly against the scalar counters:
``#stale rows == tele_stale_events_sum`` and the per-depth tally of stale
rows equals ``tele_reorg_depth_hist_sum`` (pinned by tests).

Overflow: the ring keeps the NEWEST ``capacity`` rows; ``count`` keeps the
true event total, so the host reports ``dropped = max(0, count - capacity)``
explicitly instead of silently truncating.

The scan-layout implementation lives here; the Pallas kernel re-expresses the
same masks and operands runs-last inside :mod:`tpusim.pallas_engine`, and the
two are pinned bit-equal like every other engine output.
"""

from __future__ import annotations

from typing import NamedTuple

import jax
import jax.numpy as jnp

from .state import INF_TIME, SimState

__all__ = [
    "FlightRecorder", "init_recorder", "record_step", "advance_base",
    "advance_height_base",
    "KIND_FIND", "KIND_ARRIVAL", "KIND_STALE", "KIND_REORG", "KIND_NAMES",
    "N_FIELDS", "FLIGHT_TIME_BASE",
]

I32 = jnp.int32

KIND_FIND = 0
KIND_ARRIVAL = 1
KIND_STALE = 2
KIND_REORG = 3
KIND_NAMES = ("find", "arrival", "stale", "reorg")

#: Row words: kind, miner, height, depth, t_hi, t_lo.
N_FIELDS = 6
FIELD_KIND, FIELD_MINER, FIELD_HEIGHT, FIELD_DEPTH, FIELD_T_HI, FIELD_T_LO = range(6)

#: Base of the absolute-time int32 limb pair (t_hi * 2^30 + t_lo). Matches
#: the engine's remaining-time ledger base: one chunk's elapsed is < 2^30, so
#: per-chunk accumulation carries at most one limb (engine._LEDGER_BASE).
FLIGHT_TIME_BASE = 1 << 30


class FlightRecorder(NamedTuple):
    """Per-run recorder state (one element of the vmapped batch)."""

    #: int32 [capacity, N_FIELDS] ring of packed event rows; row ``e`` of the
    #: run's event sequence lives at slot ``e % capacity``.
    buf: jax.Array
    #: int32 [] events recorded since the run started, overwritten included —
    #: the host derives the dropped count from it.
    count: jax.Array
    #: int32 [] absolute time of the current chunk origin, high limb.
    base_hi: jax.Array
    #: int32 [] low limb (< 2^30).
    base_lo: jax.Array
    #: int32 [] absolute HEIGHT of the current chunk origin: the accumulated
    #: per-chunk count-re-base total (sum over owners of the subtracted base,
    #: tpusim.state.rebase_counts). Rows store base + stored-height so the
    #: exported trace always carries absolute chain heights, exactly like
    #: the time limbs carry absolute milliseconds; stays 0 (and the adds are
    #: no-ops) when SimConfig.count_rebase is off. One int32 limb suffices —
    #: heights fit int32 for any run the block-count sum guard admits.
    h_base: jax.Array


def init_recorder(capacity: int) -> FlightRecorder:
    z = jnp.zeros((), I32)
    return FlightRecorder(jnp.zeros((capacity, N_FIELDS), I32), z, z, z, z)


def _push_row(
    fr: FlightRecorder,
    rec: jax.Array,
    kind: jax.Array,
    miner: jax.Array,
    height: jax.Array,
    depth: jax.Array,
    t: jax.Array,
) -> FlightRecorder:
    """Write one row at slot ``count % capacity`` where ``rec`` is set; the
    slot select is one-hot arithmetic (no dynamic indexing on TPU). The row's
    time fields are the UN-normalized limb pair (base_hi, base_lo + t): the
    low word can exceed 2^30 by up to one chunk span, and the host's int64
    reassembly absorbs it — no device-side carry per event."""
    capacity = fr.buf.shape[0]
    slot = jax.lax.rem(fr.count, jnp.int32(capacity))
    onehot = jnp.arange(capacity) == slot
    row = jnp.stack(
        [kind, miner, height, depth, fr.base_hi, fr.base_lo + t]
    ).astype(I32)
    buf = jnp.where((rec & onehot)[:, None], row[None, :], fr.buf)
    return fr._replace(buf=buf, count=fr.count + rec.astype(I32))


def record_step(
    fr: FlightRecorder,
    *,
    old: SimState,
    found: SimState,
    new: SimState,
    w: jax.Array,
    found_due: jax.Array,
    do: jax.Array,
) -> FlightRecorder:
    """Fold one engine step into the ring: ``old`` is the step-entry state,
    ``found`` the post-find (pre-notify) state, ``new`` the step-exit state;
    ``w`` the raw winner draw (valid where ``found_due``), ``do`` the notify
    gate. Up to two rows: find-or-arrival, then stale-or-reorg."""
    m = old.height.shape[0]
    midx = jnp.arange(m)
    t = old.t

    # Row 1 — the time event of this step (reference loop iteration kind).
    # Arrival detection uses the step-entry groups: the sweep's flush gate is
    # exactly ``do`` with flush time ``t``, and for a no-find step the
    # post-find groups are the entry groups (found_block is an identity).
    pend = jnp.where(old.group_arrival <= t, old.group_arrival, INF_TIME)
    pmin_per = jnp.min(pend, axis=-1)  # [M] earliest arrived per miner
    pmin = jnp.min(pmin_per)
    flushed = do & (pmin < INF_TIME)
    arr_miner = jnp.min(jnp.where(pmin_per == pmin, midx, m))
    rec1 = found_due | flushed
    kind1 = jnp.where(found_due, KIND_FIND, KIND_ARRIVAL)
    miner1 = jnp.where(found_due, w, arr_miner)
    h_src = jnp.where(found_due, found.height, new.height)
    height1 = jnp.sum(jnp.where(midx == miner1, h_src, 0), dtype=I32) + fr.h_base
    fr = _push_row(fr, rec1, kind1, miner1, height1, jnp.int32(0), t)

    # Row 2 — the sweep's adoption outcome. Adoption is the only height
    # change notify makes, so the found->new delta identifies adopters; the
    # stale delta is the per-adopter own-block pop count (the operands of
    # engine._count_step).
    adopt = new.height > found.height
    d_stale = new.stale - found.stale
    dmax = jnp.max(d_stale)
    rec2 = jnp.any(adopt)
    kind2 = jnp.where(dmax > 0, KIND_STALE, KIND_REORG)
    score = jnp.where(adopt, d_stale, -1)
    miner2 = jnp.min(jnp.where(adopt & (score == jnp.max(score)), midx, m))
    height2 = jnp.sum(jnp.where(midx == miner2, new.height, 0), dtype=I32) + fr.h_base
    return _push_row(fr, rec2, kind2, miner2, height2, dmax, t)


def advance_base(fr: FlightRecorder, elapsed: jax.Array) -> FlightRecorder:
    """Advance the absolute chunk origin by a re-base's ``elapsed`` (one limb
    carry suffices: elapsed < 2^30 and base_lo < 2^30)."""
    lo = fr.base_lo + elapsed
    carry = lo >= FLIGHT_TIME_BASE
    return fr._replace(
        base_hi=fr.base_hi + carry.astype(I32),
        base_lo=lo - jnp.where(carry, jnp.int32(FLIGHT_TIME_BASE), 0),
    )


def advance_height_base(fr: FlightRecorder, dh: jax.Array) -> FlightRecorder:
    """Advance the absolute height origin by a count re-base's total
    subtracted base (``sum(rebase_counts base)``) — the height twin of
    :func:`advance_base`, called at the same chunk boundary."""
    return fr._replace(h_base=fr.h_base + dh.astype(I32))
