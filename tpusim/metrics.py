"""``tpusim metrics`` / ``tpusim slo`` — the ledger-derived metrics & SLO plane.

Every observability surface before this module (telemetry spans, tracing,
perf rows) is a post-hoc file reader. This is the live plane the ROADMAP's
serve tentpole needs: a derivation layer that folds the telemetry/fleet/
tracing JSONL ledgers and the perf ledger of a state dir into counters,
gauges and **mergeable log-bucketed histograms**, an OpenMetrics text
rendition with a stdlib scrape endpoint, and a declarative SLO engine with
the perf-compare exit discipline.

    python -m tpusim metrics export fleet/            # OpenMetrics text
    python -m tpusim metrics serve --state-dir fleet/ --port 9109
    curl localhost:9109/metrics                        # scrape
    python -m tpusim slo check fleet/                  # 0 pass / 1 / 2

Deliberately jax-free, like fleet/watch/tracing: the exporter and the SLO
gate must run on a host with no backend, and the endpoint must start
instantly next to the simulation it observes. Reading is crash-tolerant the
way ``tpusim watch`` is — every scrape re-reads the state dir through the
tolerant ledger loaders (torn trailing lines and not-yet-created files
contribute zero samples, never an error), so scraping a LIVE fleet is safe
by construction.

Histograms are log-bucketed with growth factor ``HIST_BASE = 2**(1/8)``:
bucket ``i`` covers ``(HIST_BASE**(i-1), HIST_BASE**i]``, so a reported
quantile is the upper bound of its bucket and overestimates the true sample
quantile by at most ``HIST_BASE - 1`` (< 9.06% relative error); counts are
EXACT (every observation lands in exactly one bucket — the tests pin
histogram tallies equal to independently tallied span counts). Two
histograms merge by adding per-bucket counts, the arXiv:2002.01184
streaming-estimator discipline: aggregate on-line, mergeably.

The SLO engine evaluates declarative objectives (``[tool.tpusim-slo]`` in
pyproject.toml, or a JSON file) against a snapshot with ``tpusim slo
check``'s exit discipline mirroring ``perf compare``: 0 = every objective
passes, 1 = at least one violation, 2 = structural problem or dead gate (an
unknown metric name, no objectives, or an objective with NO observed data —
an empty ledger can never pass green). ``tpusim report`` and ``tpusim
watch`` render the SAME evaluator's results as their SLO panels, so the
gate and the dashboards cannot drift apart.
"""

from __future__ import annotations

import argparse
import dataclasses
import json
import math
import sys
import threading
import time
from pathlib import Path
from typing import Any, Iterable

__all__ = [
    "HIST_BASE",
    "METRICS",
    "SLO_HEADERS",
    "LogHistogram",
    "MetricsSnapshot",
    "Objective",
    "SloConfigError",
    "collect_heartbeats",
    "collect_perf_rows",
    "derive_state",
    "evaluate_slos",
    "load_objectives",
    "render_openmetrics",
    "serve_metrics",
    "slo_exit_code",
    "slo_rows",
    "snapshot_from_spans",
    "validate_openmetrics",
    "main",
    "slo_main",
]

#: Histogram bucket growth factor. Bucket upper bounds are ``HIST_BASE**i``
#: over integer ``i`` (sparse — only occupied buckets are stored), so the
#: relative quantile error is bounded by ``HIST_BASE - 1`` ~ 9.06% and two
#: histograms built anywhere merge exactly by per-bucket addition.
HIST_BASE = 2.0 ** 0.125

#: The metric registry: ``(name, type, help)`` per family — the ONE place
#: the exported metric universe is declared. ``tpusim lint`` (JX014) pins
#: this tuple against the SLO config's referenced metrics and the README
#: metrics table, so a renamed family cannot silently strand an objective
#: or a doc row. Counters are exposed with the OpenMetrics ``_total``
#: suffix; histogram quantiles carry the documented bucket error above.
METRICS = (
    ("tpusim_spans", "counter",
     "telemetry spans parsed from the state dir"),
    ("tpusim_runs", "counter",
     "simulation runs completed (batch/packed_dispatch runs attrs)"),
    ("tpusim_batch_latency_seconds", "histogram",
     "batch dispatch wall-clock (batch + packed_dispatch span durations "
     "— the same broad phase tpusim.tracing attributes)"),
    ("tpusim_compile_seconds", "histogram",
     "XLA backend compile time (compile spans)"),
    ("tpusim_checkpoint_seconds", "histogram",
     "checkpoint wall-clock by op=save|load (checkpoint_* spans)"),
    ("tpusim_query_latency_seconds", "histogram",
     "end-to-end query latency (loadgen perf-ledger samples)"),
    ("tpusim_retries", "counter",
     "batch retries (retry spans)"),
    ("tpusim_fleet_spawns", "counter",
     "fleet worker spawns (fleet_spawn spans)"),
    ("tpusim_fleet_requeues", "counter",
     "fleet point requeues (fleet_requeue spans)"),
    ("tpusim_fleet_quarantines", "counter",
     "fleet point quarantines (fleet_quarantine spans)"),
    ("tpusim_requeue_rate", "gauge",
     "fleet requeues per completed point"),
    ("tpusim_compiles_per_query", "gauge",
     "warmed-path XLA compiles per loadgen query"),
    ("tpusim_critical_path_seconds", "gauge",
     "fleet critical-path wall-clock by category (tracing attribution)"),
    ("tpusim_critical_path_coverage", "gauge",
     "attributed fraction of the fleet wall-clock window"),
    ("tpusim_heartbeat_age_seconds", "gauge",
     "age of each fleet worker's newest heartbeat, by worker"),
    ("tpusim_stat_rel_halfwidth", "gauge",
     "per-statistic 95% CI relative half-width (newest stats span)"),
    ("tpusim_serve_latency_seconds", "histogram",
     "accept-to-answer latency of served queries (serve_query spans)"),
    ("tpusim_serve_queue_depth", "histogram",
     "request-queue depth sampled at each admission (serve_accept spans)"),
    ("tpusim_serve_queries", "counter",
     "service queries by status=served|shed|rejected "
     "(serve_query/serve_reject spans)"),
    ("tpusim_serve_shed_ratio", "gauge",
     "shed fraction of resolved service queries, shed/(served+shed)"),
)

_TYPES = {name: kind for name, kind, _ in METRICS}
_HELP = {name: text for name, _, text in METRICS}

#: Label-set key: sorted ``(key, value)`` pairs — hashable, order-free.
Labels = tuple


def _labels_key(labels: dict[str, str] | None) -> Labels:
    return tuple(sorted((str(k), str(v)) for k, v in (labels or {}).items()))


class LogHistogram:
    """A mergeable log-bucketed histogram (sparse ``index -> count``).

    ``observe(v)`` files ``v`` under the smallest integer ``i`` with
    ``HIST_BASE**i >= v`` (non-positive values under the explicit zero
    bucket), tracking exact ``count``/``sum``. ``quantile(q)`` reports the
    upper bound of the bucket holding the q-th sample — an overestimate by
    at most ``HIST_BASE - 1`` relative. ``merge`` adds per-bucket counts:
    the result is IDENTICAL to observing both streams into one histogram,
    which is what makes per-worker histograms foldable into fleet ones.
    """

    __slots__ = ("counts", "zero", "count", "sum")

    def __init__(self) -> None:
        self.counts: dict[int, int] = {}
        self.zero = 0
        self.count = 0
        self.sum = 0.0

    def observe(self, value: float) -> None:
        value = float(value)
        self.count += 1
        self.sum += value
        if value <= 0.0:
            self.zero += 1
            return
        # The epsilon keeps exact powers of the base in their own bucket
        # (log() noise must not push base**i into bucket i+1).
        idx = math.ceil(math.log(value, HIST_BASE) - 1e-9)
        self.counts[idx] = self.counts.get(idx, 0) + 1

    def merge(self, other: "LogHistogram") -> None:
        self.zero += other.zero
        self.count += other.count
        self.sum += other.sum
        for idx, n in other.counts.items():
            self.counts[idx] = self.counts.get(idx, 0) + n

    def quantile(self, q: float) -> float | None:
        """Upper bound of the bucket holding sample rank ``ceil(q*count)``;
        None on an empty histogram (no-data, never a fake zero)."""
        if self.count == 0:
            return None
        rank = max(1, math.ceil(min(max(q, 0.0), 1.0) * self.count))
        seen = self.zero
        if rank <= seen:
            return 0.0
        for idx in sorted(self.counts):
            seen += self.counts[idx]
            if rank <= seen:
                return HIST_BASE ** idx
        return HIST_BASE ** max(self.counts)  # pragma: no cover - rank<=count

    def buckets(self) -> list[tuple[float, int]]:
        """Cumulative ``(le, count)`` pairs in ascending ``le`` order, the
        OpenMetrics ``_bucket`` shape (the +Inf bucket is the renderer's)."""
        out: list[tuple[float, int]] = []
        cum = 0
        if self.zero:
            cum += self.zero
            out.append((0.0, cum))
        for idx in sorted(self.counts):
            cum += self.counts[idx]
            out.append((HIST_BASE ** idx, cum))
        return out


@dataclasses.dataclass
class MetricsSnapshot:
    """One derived snapshot: per-family series keyed by label set. The
    constructor-free helpers enforce the registry — a typo'd family name is
    a programming error here, never a silently invented metric."""

    counters: dict[str, dict[Labels, float]] = dataclasses.field(default_factory=dict)
    gauges: dict[str, dict[Labels, float]] = dataclasses.field(default_factory=dict)
    hists: dict[str, dict[Labels, LogHistogram]] = dataclasses.field(default_factory=dict)
    meta: dict[str, Any] = dataclasses.field(default_factory=dict)

    def _check(self, name: str, kind: str) -> None:
        if _TYPES.get(name) != kind:
            raise ValueError(
                f"metric {name!r} is not a registered {kind} "
                f"(registry: {_TYPES.get(name)!r}) — add it to METRICS first"
            )

    def counter_add(self, name: str, value: float, labels: dict | None = None) -> None:
        self._check(name, "counter")
        series = self.counters.setdefault(name, {})
        key = _labels_key(labels)
        series[key] = series.get(key, 0.0) + float(value)

    def gauge_set(self, name: str, value: float, labels: dict | None = None) -> None:
        self._check(name, "gauge")
        self.gauges.setdefault(name, {})[_labels_key(labels)] = float(value)

    def observe(self, name: str, value: float, labels: dict | None = None) -> None:
        self._check(name, "histogram")
        series = self.hists.setdefault(name, {})
        key = _labels_key(labels)
        if key not in series:
            series[key] = LogHistogram()
        series[key].observe(value)

    def merged_hist(self, name: str, want: Labels = ()) -> LogHistogram:
        """One histogram over every series of ``name`` whose labels contain
        ``want`` as a subset — the evaluator's aggregation primitive."""
        out = LogHistogram()
        for key, h in (self.hists.get(name) or {}).items():
            if set(want) <= set(key):
                out.merge(h)
        return out


# ---------------------------------------------------------------------------
# Derivation: ledgers -> snapshot.


def snapshot_from_spans(
    spans: list[dict],
    perf_rows: Iterable[dict] = (),
    heartbeats: Iterable[tuple[str, float]] = (),
    now: float | None = None,
) -> MetricsSnapshot:
    """Fold telemetry/fleet spans (the tolerant ``load_spans`` shape), perf
    ledger rows and worker heartbeats into one snapshot. Every attr read is
    ``.get``-based with a None-tolerant default — a torn or foreign ledger
    contributes zero samples, never a crash (the JX010 dashboard rule)."""
    if now is None:
        now = time.time()
    snap = MetricsSnapshot()
    snap.counter_add("tpusim_spans", len(spans))

    last_stats: dict | None = None
    serve_outcomes: dict[str, int] = {}
    for sp in spans:
        name = sp.get("span")
        dur = float(sp.get("dur_s") or 0.0)
        attrs = sp.get("attrs") or {}
        if name in ("batch", "packed_dispatch"):
            # One dispatch histogram across both execution paths — the same
            # batch/packed_dispatch equivalence tpusim.tracing's broad-phase
            # attribution uses, so a packed fleet feeds the latency SLO too.
            snap.observe("tpusim_batch_latency_seconds", dur)
            snap.counter_add("tpusim_runs", int(attrs.get("runs") or 0))
        elif name == "compile":
            snap.observe("tpusim_compile_seconds", dur)
        elif name == "checkpoint_save":
            snap.observe("tpusim_checkpoint_seconds", dur, {"op": "save"})
        elif name == "checkpoint_load":
            snap.observe("tpusim_checkpoint_seconds", dur, {"op": "load"})
        elif name == "retry":
            snap.counter_add("tpusim_retries", 1)
        elif name == "fleet_spawn":
            snap.counter_add("tpusim_fleet_spawns", 1)
        elif name == "fleet_requeue":
            snap.counter_add("tpusim_fleet_requeues", 1)
        elif name == "fleet_quarantine":
            snap.counter_add("tpusim_fleet_quarantines", 1)
        elif name == "serve_accept":
            depth = attrs.get("depth")
            if isinstance(depth, (int, float)) and not isinstance(depth, bool):
                snap.observe("tpusim_serve_queue_depth", float(depth))
        elif name == "serve_query":
            status = str(attrs.get("status") or "unknown")
            snap.counter_add("tpusim_serve_queries", 1, {"status": status})
            serve_outcomes[status] = serve_outcomes.get(status, 0) + 1
            if status == "served":
                snap.observe("tpusim_serve_latency_seconds", dur)
        elif name == "serve_reject":
            snap.counter_add("tpusim_serve_queries", 1, {"status": "rejected"})
        elif name == "stats":
            last_stats = attrs

    # Per-stat CI half-widths from the NEWEST stats span — the convergence
    # state the watch dashboard follows, as scrapeable gauges.
    if last_stats is not None:
        per_stat = last_stats.get("stats") or {}
        for stat, entry in per_stat.items():
            rel = entry.get("rel_hw_max") if isinstance(entry, dict) else None
            if isinstance(rel, (int, float)) and not isinstance(rel, bool):
                snap.gauge_set(
                    "tpusim_stat_rel_halfwidth", float(rel), {"stat": str(stat)}
                )

    # Service shed ratio: shed over resolved (served + shed). Rejections are
    # admission control doing its job, so they count in tpusim_serve_queries
    # but not against the shed ceiling.
    resolved = serve_outcomes.get("served", 0) + serve_outcomes.get("shed", 0)
    if resolved:
        snap.gauge_set(
            "tpusim_serve_shed_ratio", serve_outcomes.get("shed", 0) / resolved
        )

    # Fleet summary -> requeue rate (the same shared extraction both
    # dashboards render from, so the gauge cannot drift from the panels).
    from .fleet import summarize_fleet_spans

    fleet = summarize_fleet_spans(spans)
    if fleet is not None:
        requeues = len(fleet["requeues"])
        points = fleet["points_done"]
        points = int(points) if isinstance(points, (int, float)) else 0
        snap.gauge_set("tpusim_requeue_rate", requeues / max(points, 1))
        snap.meta["fleet"] = {
            "points_done": fleet["points_done"],
            "points_total": fleet["points_total"],
            "workers_alive": fleet["workers_alive"],
            "quarantined": fleet["quarantined"],
        }

    # Cross-process critical-path attribution (tpusim.tracing): category
    # seconds + coverage, when the ledgers correlate into a trace.
    from .tracing import assemble, attribution

    trace = assemble(spans)
    if trace is not None and any(
        node.process is not None for node in trace.workers.values()
    ):
        att = attribution(trace)
        for category, seconds in att["categories"].items():
            snap.gauge_set(
                "tpusim_critical_path_seconds", seconds,
                {"category": str(category)},
            )
        snap.gauge_set("tpusim_critical_path_coverage", att["coverage"])

    for worker, last_t in heartbeats:
        snap.gauge_set(
            "tpusim_heartbeat_age_seconds",
            max(now - float(last_t), 0.0),
            {"worker": str(worker)},
        )

    for row in perf_rows:
        scenario = row.get("scenario")
        metric = row.get("metric")
        if scenario != "loadgen":
            continue
        if metric == "query_latency_s":
            for s in row.get("samples") or []:
                if isinstance(s, (int, float)) and not isinstance(s, bool):
                    snap.observe("tpusim_query_latency_seconds", float(s))
        elif metric == "compiles_per_query":
            value = row.get("value")
            if isinstance(value, (int, float)) and not isinstance(value, bool):
                snap.gauge_set("tpusim_compiles_per_query", float(value))

    snap.meta.setdefault("derived_at", now)
    return snap


def collect_heartbeats(root: Path) -> list[tuple[str, float]]:
    """Newest heartbeat timestamp per worker from ``**/*.hb.jsonl`` under
    ``root`` — tolerant per line (a beat being appended mid-scrape is a
    torn line, not an error)."""
    out: list[tuple[str, float]] = []
    if not root.is_dir():
        return out
    for path in sorted(root.rglob("*.hb.jsonl")):
        last_t: float | None = None
        try:
            lines = path.read_text().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            t = row.get("t") if isinstance(row, dict) else None
            if isinstance(t, (int, float)) and not isinstance(t, bool):
                last_t = float(t)
        if last_t is not None:
            out.append((path.name[: -len(".hb.jsonl")], last_t))
    return out


def collect_perf_rows(root: Path) -> list[dict]:
    """Schema-valid perf rows from every ``*.jsonl`` under ``root`` (or the
    file itself). TOLERANT, unlike ``perf.load_rows``: a live state dir's
    ledgers are foreign (telemetry spans, heartbeats) or torn mid-append,
    and a scrape must surface what parses, not die on what doesn't."""
    from .perf import SCHEMA, validate_row

    files: list[Path]
    if root.is_dir():
        files = sorted(root.rglob("*.jsonl"))
    elif root.exists():
        files = [root]
    else:
        return []
    rows: list[dict] = []
    for path in files:
        try:
            lines = path.read_text().splitlines()
        except OSError:
            continue
        for line in lines:
            try:
                row = json.loads(line)
            except json.JSONDecodeError:
                continue
            if not isinstance(row, dict) or row.get("schema") != SCHEMA:
                continue
            try:
                validate_row(row)
            except ValueError:
                continue
            rows.append(row)
    return rows


def derive_state(path: str | Path, now: float | None = None) -> MetricsSnapshot:
    """The one-call derivation behind every surface: state dir (or single
    ledger file) -> snapshot. A missing path yields an EMPTY snapshot (the
    endpoint must tolerate a not-yet-created state dir); the SLO dead-gate
    discipline is what keeps empty from passing green."""
    from .tracing import collect_spans

    p = Path(path)
    spans = collect_spans([p])
    snap = snapshot_from_spans(
        spans,
        perf_rows=collect_perf_rows(p),
        heartbeats=collect_heartbeats(p),
        now=now,
    )
    snap.meta["source"] = str(p)
    return snap


# ---------------------------------------------------------------------------
# OpenMetrics rendition.


def _fmt_float(v: float) -> str:
    if v == math.inf:
        return "+Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return format(float(v), ".9g")


def _escape_label(v: str) -> str:
    return v.replace("\\", "\\\\").replace('"', '\\"').replace("\n", "\\n")


def _label_str(key: Labels, extra: str = "") -> str:
    parts = [f'{k}="{_escape_label(v)}"' for k, v in key]
    if extra:
        parts.append(extra)
    return "{" + ",".join(parts) + "}" if parts else ""


def render_openmetrics(snap: MetricsSnapshot) -> str:
    """The snapshot as OpenMetrics text: every registry family gets its
    ``# TYPE``/``# HELP`` header (absent series render no samples — the SLO
    evaluator treats that as no-data, never as zero), counters carry the
    ``_total`` suffix, histograms the cumulative ``_bucket{le=}``/``_sum``/
    ``_count`` triple, and the exposition ends with ``# EOF``."""
    out: list[str] = []
    for name, kind, help_text in METRICS:
        out.append(f"# TYPE {name} {kind}")
        out.append(f"# HELP {name} {help_text}")
        if kind == "counter":
            series = snap.counters.get(name) or {}
            for key in sorted(series):
                out.append(
                    f"{name}_total{_label_str(key)} "
                    f"{_fmt_float(series[key])}"
                )
        elif kind == "gauge":
            series_g = snap.gauges.get(name) or {}
            for key in sorted(series_g):
                out.append(f"{name}{_label_str(key)} {_fmt_float(series_g[key])}")
        else:
            series_h = snap.hists.get(name) or {}
            for key in sorted(series_h):
                h = series_h[key]
                for le, cum in h.buckets():
                    le_lbl = f'le="{_fmt_float(le)}"'
                    out.append(f"{name}_bucket{_label_str(key, le_lbl)} {cum}")
                inf_lbl = 'le="+Inf"'
                out.append(f"{name}_bucket{_label_str(key, inf_lbl)} {h.count}")
                out.append(f"{name}_sum{_label_str(key)} {_fmt_float(h.sum)}")
                out.append(f"{name}_count{_label_str(key)} {h.count}")
    out.append("# EOF")
    return "\n".join(out) + "\n"


def validate_openmetrics(text: str) -> int:
    """Strict structural validation of an exposition (the harvest/CI
    check): declared families only, counters ``_total``-suffixed,
    histogram buckets cumulative with ``+Inf == _count``, ``# EOF``
    terminated. Returns the sample-line count; raises ValueError."""
    lines = text.splitlines()
    if not lines or lines[-1] != "# EOF":
        raise ValueError("exposition must end with a '# EOF' line")
    declared: dict[str, str] = {}
    samples = 0
    hist_state: dict[str, dict[str, Any]] = {}
    for i, line in enumerate(lines[:-1], start=1):
        if not line:
            raise ValueError(f"line {i}: blank line inside exposition")
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            fam, _, kind = rest.partition(" ")
            if kind not in ("counter", "gauge", "histogram"):
                raise ValueError(f"line {i}: unknown TYPE {kind!r}")
            declared[fam] = kind
            continue
        if line.startswith("#"):
            continue
        samples += 1
        metric_name = line.split("{", 1)[0].split(" ", 1)[0]
        fam, suffix = metric_name, ""
        for cand in ("_total", "_bucket", "_sum", "_count"):
            if metric_name.endswith(cand) and metric_name[: -len(cand)] in declared:
                fam, suffix = metric_name[: -len(cand)], cand
                break
        kind = declared.get(fam)
        if kind is None:
            raise ValueError(f"line {i}: sample for undeclared family {metric_name!r}")
        if kind == "counter" and suffix != "_total":
            raise ValueError(f"line {i}: counter sample must end in _total")
        if kind == "gauge" and suffix:
            raise ValueError(f"line {i}: gauge sample must be bare-named")
        if kind == "histogram":
            if suffix not in ("_bucket", "_sum", "_count"):
                raise ValueError(
                    f"line {i}: histogram sample needs _bucket/_sum/_count"
                )
            value = float(line.rsplit(" ", 1)[1])
            labels = line.split("{", 1)[1].rsplit("}", 1)[0] if "{" in line else ""
            series_key = fam + "|" + ",".join(
                p for p in labels.split(",") if not p.startswith("le=")
            )
            st = hist_state.setdefault(
                series_key, {"prev": -1.0, "inf": None, "count": None}
            )
            if suffix == "_bucket":
                if "le=" not in labels:
                    raise ValueError(f"line {i}: _bucket sample without le=")
                if 'le="+Inf"' in labels:
                    st["inf"] = value
                elif value < st["prev"]:
                    raise ValueError(f"line {i}: non-cumulative bucket counts")
                else:
                    st["prev"] = value
            elif suffix == "_count":
                st["count"] = value
    for key, st in hist_state.items():
        if st["inf"] is None or st["count"] is None:
            raise ValueError(f"histogram series {key}: missing +Inf bucket or _count")
        if st["inf"] != st["count"]:
            raise ValueError(
                f"histogram series {key}: +Inf bucket {st['inf']} != _count {st['count']}"
            )
        if st["prev"] > st["inf"]:
            raise ValueError(f"histogram series {key}: bucket exceeds +Inf")
    return samples


# ---------------------------------------------------------------------------
# SLO engine.


class SloConfigError(ValueError):
    """A structurally broken SLO config — always exit 2, never a pass."""


_OPS = {
    "<=": lambda v, t: v <= t,
    ">=": lambda v, t: v >= t,
    "==": lambda v, t: v == t,
}

_STATS = ("value", "p50", "p95", "p99", "count", "sum", "mean")


@dataclasses.dataclass(frozen=True)
class Objective:
    """One declarative objective: ``<metric>[labels] <stat> <op> <threshold>``."""

    metric: str
    op: str
    threshold: float
    stat: str = "value"
    name: str = ""
    labels: Labels = ()
    #: Gate grouping: ``slo check --profile X`` evaluates only profile-X
    #: objectives, so the serve gate and the batch/fleet gate each stay a
    #: live gate over state dirs that only ever contain their own spans
    #: (a serve-less fleet dir must not turn the whole check into no-data).
    profile: str = "default"

    def describe(self) -> str:
        return self.name or f"{self.metric}.{self.stat}{self.op}{self.threshold:g}"


def _objective_from_dict(row: Any, source: str) -> Objective:
    if not isinstance(row, dict):
        raise SloConfigError(f"{source}: objective must be an object, got {row!r}")
    metric = row.get("metric")
    if not isinstance(metric, str) or not metric:
        raise SloConfigError(f"{source}: objective needs a string 'metric'")
    op = row.get("op", "<=")
    if op not in _OPS:
        raise SloConfigError(f"{source}: objective op must be one of {sorted(_OPS)}")
    threshold = row.get("threshold")
    if not isinstance(threshold, (int, float)) or isinstance(threshold, bool):
        raise SloConfigError(f"{source}: objective needs a numeric 'threshold'")
    stat = row.get("stat", "value")
    if stat not in _STATS:
        raise SloConfigError(f"{source}: objective stat must be one of {_STATS}")
    labels = row.get("labels") or {}
    if not isinstance(labels, dict):
        raise SloConfigError(f"{source}: objective labels must be an object")
    profile = row.get("profile", "default")
    if not isinstance(profile, str) or not profile:
        raise SloConfigError(f"{source}: objective profile must be a "
                             f"non-empty string")
    return Objective(
        metric=metric, op=op, threshold=float(threshold), stat=stat,
        name=str(row.get("name", "")), labels=_labels_key(labels),
        profile=profile,
    )


def load_objectives(
    config_path: str | Path | None = None, root: str | Path | None = None,
    profile: str | None = None,
) -> list[Objective]:
    """Objectives from an explicit JSON/TOML file, or from the repo's
    committed ``[tool.tpusim-slo]`` pyproject block (``objectives`` array of
    tables). ``profile`` narrows to one gate's objectives (None = all — the
    dashboards' view). Raises :class:`SloConfigError` on anything structural
    — missing file, no parser, empty/zero objectives, a profile filter that
    matches nothing — because a gate with no objectives is a dead gate
    (exit 2), not a vacuous pass."""
    if config_path is None:
        pyproject = Path(root) / "pyproject.toml" if root is not None else (
            Path(__file__).resolve().parents[1] / "pyproject.toml"
        )
        config_path = pyproject
    p = Path(config_path)
    if not p.exists():
        raise SloConfigError(f"SLO config {p} does not exist")
    if p.suffix == ".json":
        try:
            data = json.loads(p.read_text())
        except (OSError, json.JSONDecodeError) as e:
            raise SloConfigError(f"{p}: unparseable JSON SLO config ({e})") from None
        rows = data.get("objectives") if isinstance(data, dict) else None
    else:
        from .lint.config import _toml

        if _toml is None:
            raise SloConfigError(
                f"{p}: no TOML parser available (need tomllib/tomli) — pass "
                f"a JSON config via --config instead"
            )
        try:
            with p.open("rb") as fh:
                data = _toml.load(fh)
        except (OSError, ValueError) as e:
            raise SloConfigError(f"{p}: unparseable TOML ({e})") from None
        rows = data.get("tool", {}).get("tpusim-slo", {}).get("objectives")
    if not isinstance(rows, list) or not rows:
        raise SloConfigError(
            f"{p}: no SLO objectives found (need a non-empty 'objectives' "
            f"array) — an objective-less gate is a dead gate"
        )
    objectives = [_objective_from_dict(row, str(p)) for row in rows]
    if profile is not None:
        known = sorted({o.profile for o in objectives})
        objectives = [o for o in objectives if o.profile == profile]
        if not objectives:
            raise SloConfigError(
                f"{p}: no objectives in profile {profile!r} (profiles "
                f"declared: {known}) — an objective-less gate is a dead gate"
            )
    return objectives


def _observed(obj: Objective, snap: MetricsSnapshot) -> tuple[float | None, str]:
    """(observed value, status-reason). None value => no data."""
    kind = _TYPES.get(obj.metric)
    if kind is None:
        return None, "unknown metric (not in the registry)"
    if kind == "histogram":
        h = snap.merged_hist(obj.metric, obj.labels)
        if h.count == 0:
            return None, "no samples"
        if obj.stat == "count":
            return float(h.count), ""
        if obj.stat == "sum":
            return h.sum, ""
        if obj.stat == "mean":
            return h.sum / h.count, ""
        if obj.stat in ("p50", "p95", "p99"):
            return h.quantile(int(obj.stat[1:]) / 100.0), ""
        return None, f"stat {obj.stat!r} needs a quantile/count/sum on a histogram"
    series = (snap.counters if kind == "counter" else snap.gauges).get(obj.metric) or {}
    matched = [v for k, v in series.items() if set(obj.labels) <= set(k)]
    if not matched:
        return None, "no samples"
    if obj.stat != "value":
        return None, f"stat {obj.stat!r} is histogram-only"
    if kind == "counter":
        return float(sum(matched)), ""
    # Gauge with several matched series: aggregate to the WORST side of the
    # objective (max for <=/==, min for >=) so a passing aggregate implies
    # every matched series passes.
    return (min(matched) if obj.op == ">=" else max(matched)), ""


def evaluate_slos(
    objectives: list[Objective], snap: MetricsSnapshot
) -> list[dict[str, Any]]:
    """One result row per objective: status ``pass`` / ``violation`` /
    ``no-data`` (with a reason). THE shared evaluator: ``slo check`` exits
    from these rows and both dashboards render them."""
    results = []
    for obj in objectives:
        observed, reason = _observed(obj, snap)
        if observed is None:
            status = "no-data"
        elif _OPS[obj.op](observed, obj.threshold):
            status = "pass"
        else:
            status = "violation"
        results.append({
            "objective": obj,
            "status": status,
            "observed": observed,
            "reason": reason,
        })
    return results


def slo_exit_code(results: list[dict[str, Any]]) -> int:
    """The perf-compare discipline: structural/no-data dominates (2 — a
    dead gate must fail loud before a violation is even reported), then
    violation (1), then pass (0). An empty result list is itself a dead
    gate."""
    if not results or any(r["status"] == "no-data" for r in results):
        return 2
    if any(r["status"] == "violation" for r in results):
        return 1
    return 0


SLO_HEADERS = ["objective", "metric", "stat", "target", "observed", "status"]


def slo_rows(results: list[dict[str, Any]]) -> list[list[str]]:
    """Render-ready rows for ``text_table`` — shared by ``slo check``,
    ``tpusim report`` and ``tpusim watch`` (one source of truth, no
    drifting twin renderers)."""
    rows = []
    for r in results:
        obj: Objective = r["objective"]
        observed = r["observed"]
        status = r["status"].upper()
        if r["status"] == "no-data" and r["reason"]:
            status += f" ({r['reason']})"
        rows.append([
            obj.describe(),
            obj.metric + (_label_str(obj.labels) if obj.labels else ""),
            obj.stat,
            f"{obj.op} {obj.threshold:g}",
            f"{observed:g}" if observed is not None else "n/a",
            status,
        ])
    return rows


# ---------------------------------------------------------------------------
# HTTP endpoint (stdlib only).

#: OpenMetrics scrape content type (the standard exposition negotiation).
CONTENT_TYPE = "application/openmetrics-text; version=1.0.0; charset=utf-8"


def _summary_payload(snap: MetricsSnapshot, results: list[dict] | None) -> dict:
    quantiles = {}
    for name, series in snap.hists.items():
        h = snap.merged_hist(name)
        if h.count:
            quantiles[name] = {
                "count": h.count,
                "sum": round(h.sum, 6),
                "p50": h.quantile(0.5),
                "p95": h.quantile(0.95),
                "p99": h.quantile(0.99),
            }
    payload: dict[str, Any] = {
        "counters": {
            name: sum(series.values())
            for name, series in snap.counters.items()
        },
        "gauges": {
            name: {",".join(f"{k}={v}" for k, v in key) or "_": value
                   for key, value in series.items()}
            for name, series in snap.gauges.items()
        },
        "histograms": quantiles,
        "meta": snap.meta,
    }
    if results is not None:
        payload["slo"] = [
            {
                "objective": r["objective"].describe(),
                "metric": r["objective"].metric,
                "status": r["status"],
                "observed": r["observed"],
            }
            for r in results
        ]
    return payload


def serve_metrics(
    state_dir: str | Path,
    host: str = "127.0.0.1",
    port: int = 0,
    objectives: list[Objective] | None = None,
):
    """Build (not start) the scrape server: a stdlib ``ThreadingHTTPServer``
    whose handler re-derives the snapshot from the state dir ON EVERY
    request — the watch discipline (torn lines and missing files are
    tolerated by the loaders underneath), so scraping a live fleet needs no
    coordination with it. Routes: ``/metrics`` (OpenMetrics), ``/healthz``
    (liveness + readiness JSON), ``/api/summary`` (JSON digest + SLO
    status). Returns the server; callers drive ``serve_forever`` and
    ``shutdown``."""
    from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

    state = Path(state_dir)

    class Handler(BaseHTTPRequestHandler):
        def _send(self, code: int, body: bytes, ctype: str) -> None:
            self.send_response(code)
            self.send_header("Content-Type", ctype)
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            self.wfile.write(body)

        def do_GET(self) -> None:  # noqa: N802 - BaseHTTPRequestHandler API
            path = self.path.split("?", 1)[0]
            try:
                if path == "/metrics":
                    snap = derive_state(state)
                    self._send(
                        200, render_openmetrics(snap).encode(), CONTENT_TYPE
                    )
                elif path == "/healthz":
                    snap = derive_state(state)
                    spans = sum(
                        (snap.counters.get("tpusim_spans") or {}).values()
                    )
                    body = json.dumps({
                        "ok": True,
                        "state_dir": str(state),
                        "state_dir_exists": state.exists(),
                        "spans": int(spans),
                        "ready": spans > 0,
                    }).encode()
                    self._send(200, body, "application/json")
                elif path == "/api/summary":
                    snap = derive_state(state)
                    results = (
                        evaluate_slos(objectives, snap)
                        if objectives else None
                    )
                    body = json.dumps(
                        _summary_payload(snap, results)
                    ).encode()
                    self._send(200, body, "application/json")
                else:
                    self._send(404, b'{"error": "not found"}', "application/json")
            except BrokenPipeError:  # scraper hung up mid-response
                pass
            except Exception as e:  # noqa: BLE001 - a scrape must never kill the server
                try:
                    self._send(
                        500,
                        json.dumps({"error": str(e)}).encode(),
                        "application/json",
                    )
                except OSError:
                    pass

        def log_message(self, *args) -> None:  # quiet by default
            pass

    return ThreadingHTTPServer((host, port), Handler)


# ---------------------------------------------------------------------------
# CLI: `tpusim metrics ...` and `tpusim slo ...`.


def main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim metrics",
        description="Ledger-derived metrics: OpenMetrics export and the "
        "live scrape endpoint over a telemetry state dir.",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)

    p_exp = sub.add_parser("export", help="render a state dir as OpenMetrics text")
    p_exp.add_argument("path", type=Path, help="state dir or telemetry .jsonl ledger")
    p_exp.add_argument("--out", type=Path, help="also write the exposition here")

    p_srv = sub.add_parser("serve", help="HTTP scrape endpoint over a live state dir")
    p_srv.add_argument(
        "--state-dir", type=Path, required=True, metavar="DIR",
        help="state dir (or ledger file) re-read tolerantly on every scrape; "
        "may not exist yet — /healthz reports ready:false until spans land",
    )
    p_srv.add_argument("--host", default="127.0.0.1")
    p_srv.add_argument(
        "--port", type=int, default=9109,
        help="TCP port (0 = ephemeral; the chosen port is printed)",
    )
    p_srv.add_argument(
        "--once", action="store_true",
        help="bind, self-scrape /metrics + /healthz once (validated), print "
        "both, and exit — the CI smoke mode",
    )
    p_srv.add_argument(
        "--slo-config", type=Path, metavar="FILE",
        help="JSON/TOML objectives for /api/summary's SLO status (default: "
        "the repo pyproject's [tool.tpusim-slo] block, if readable)",
    )
    args = ap.parse_args(argv)

    if args.cmd == "export":
        if not args.path.exists():
            print(f"error: {args.path} does not exist", file=sys.stderr)
            return 2
        text = render_openmetrics(derive_state(args.path))
        try:
            print(text, end="")
        except BrokenPipeError:
            pass
        if args.out:
            args.out.parent.mkdir(parents=True, exist_ok=True)
            args.out.write_text(text)
        return 0

    # serve
    objectives: list[Objective] | None = None
    try:
        objectives = load_objectives(args.slo_config)
    except SloConfigError as e:
        if args.slo_config is not None:
            # An EXPLICIT config that does not parse is an error; the
            # implicit pyproject default is best-effort for /api/summary.
            print(f"error: {e}", file=sys.stderr)
            return 2
    server = serve_metrics(
        args.state_dir, host=args.host, port=args.port, objectives=objectives
    )
    host, port = server.server_address[:2]
    print(f"[metrics] serving {args.state_dir} on http://{host}:{port}/metrics")
    if args.once:
        import urllib.request

        thread = threading.Thread(target=server.serve_forever, daemon=True)
        thread.start()
        try:
            with urllib.request.urlopen(
                f"http://{host}:{port}/metrics", timeout=30
            ) as resp:
                body = resp.read().decode()
                ctype = resp.headers.get("Content-Type", "")
            if resp.status != 200 or "openmetrics-text" not in ctype:
                print(
                    f"error: /metrics scrape failed (status {resp.status}, "
                    f"content-type {ctype!r})", file=sys.stderr,
                )
                return 1
            n = validate_openmetrics(body)
            with urllib.request.urlopen(
                f"http://{host}:{port}/healthz", timeout=30
            ) as resp:
                health = json.loads(resp.read().decode())
            print(body, end="")
            print(f"[metrics] --once scrape OK: {n} samples, healthz {health}")
            return 0
        except (OSError, ValueError) as e:
            print(f"error: --once self-scrape failed: {e}", file=sys.stderr)
            return 1
        finally:
            server.shutdown()
            server.server_close()
    try:
        server.serve_forever()
    except KeyboardInterrupt:
        print()
    finally:
        server.server_close()
    return 0


def slo_main(argv: list[str] | None = None) -> int:
    ap = argparse.ArgumentParser(
        prog="tpusim slo",
        description="Declarative service objectives over the metrics plane "
        "(exit 0 pass / 1 violation / 2 structural-or-dead-gate).",
    )
    sub = ap.add_subparsers(dest="cmd", required=True)
    p_chk = sub.add_parser("check", help="evaluate the objectives against a state dir")
    p_chk.add_argument("path", type=Path, help="state dir or telemetry .jsonl ledger")
    p_chk.add_argument(
        "--config", type=Path, metavar="FILE",
        help="JSON (.json) or TOML objectives file (default: the repo "
        "pyproject's [tool.tpusim-slo] block)",
    )
    p_chk.add_argument(
        "--profile", default="default", metavar="NAME",
        help="objective profile to gate on (objectives declare `profile`; "
        "unmarked ones are profile 'default', the serve gate is 'serve')",
    )
    args = ap.parse_args(argv)

    try:
        objectives = load_objectives(args.config, profile=args.profile)
    except SloConfigError as e:
        print(f"error: {e}", file=sys.stderr)
        return 2
    if not args.path.exists():
        print(f"error: {args.path} does not exist (a gate over a missing "
              f"state dir is a dead gate)", file=sys.stderr)
        return 2
    snap = derive_state(args.path)
    results = evaluate_slos(objectives, snap)
    from .report import text_table

    print("\n".join(text_table(SLO_HEADERS, slo_rows(results))))
    rc = slo_exit_code(results)
    if rc == 2:
        print(
            "error: SLO gate is structurally dead (no-data objective or no "
            "objectives) — an empty ledger can never pass green",
            file=sys.stderr,
        )
    elif rc == 1:
        n = sum(1 for r in results if r["status"] == "violation")
        print(f"error: {n} SLO violation(s)", file=sys.stderr)
    return rc


if __name__ == "__main__":
    sys.exit(main())
