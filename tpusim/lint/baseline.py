"""Baseline file: grandfathered findings the CI gate tolerates.

The file is committed JSON — a sorted list of fingerprint records plus the
rule/path/message at write time (for humans reading the diff; matching uses
only the fingerprint). ``tpusim lint --baseline FILE`` subtracts matching
findings; ``--write-baseline`` rewrites the file from the current findings,
which is also how a fixed finding leaves the baseline (the shrinking diff is
the progress record).
"""

from __future__ import annotations

import json
from pathlib import Path

from .findings import Finding, fingerprint_findings


class Baseline:
    VERSION = 1

    def __init__(self, fingerprints: set[str] | None = None):
        self.fingerprints = fingerprints or set()

    @classmethod
    def load(cls, path: Path) -> "Baseline":
        if not path.exists():
            return cls()
        data = json.loads(path.read_text())
        if data.get("version") != cls.VERSION:
            raise ValueError(
                f"baseline {path} has version {data.get('version')!r}; this "
                f"tpusim-lint reads version {cls.VERSION} — regenerate with "
                f"--write-baseline"
            )
        return cls({rec["fingerprint"] for rec in data.get("findings", [])})

    @staticmethod
    def write(path: Path, findings: list[Finding]) -> None:
        records = [
            {
                "fingerprint": fp,
                "rule": f.rule,
                "path": f.path,
                "line": f.line,  # informational: matching ignores it
                "message": f.message,
            }
            for f, fp in fingerprint_findings(findings)
        ]
        records.sort(key=lambda r: r["fingerprint"])
        path.write_text(
            json.dumps({"version": Baseline.VERSION, "findings": records}, indent=2)
            + "\n"
        )

    def split(self, findings: list[Finding]) -> tuple[list[Finding], list[Finding]]:
        """(new, grandfathered) — new findings fail the gate."""
        new, old = [], []
        for f, fp in fingerprint_findings(findings):
            (old if fp in self.fingerprints else new).append(f)
        return new, old
