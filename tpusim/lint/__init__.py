"""``tpusim lint`` — a project-aware static analyzer for the JAX hygiene
invariants this codebase's three dispatch paths (scan, pallas, pipelined)
depend on but no runtime test can see until they break on hardware.

The failure modes are the ones the TPU Monte-Carlo literature (Ising-on-TPU,
tfp.mcmc on TPU — PAPERS.md) and this repo's own PR history keep rediscovering:
host syncs hidden in hot loops, donated buffers read after the donating call,
tracer-typed Python branches that silently retrace, dtype drift under the x64
compat shim, and recompilation inside dispatch loops. Each is an AST-visible
pattern; catching them at review time is the cheapest correctness tooling we
can add ahead of a TPU-tunnel session.

Rules (see :mod:`tpusim.lint.rules` for the precise semantics):

  JX001  Python ``if``/``while`` on tracer-typed values in jit-reachable code
  JX002  implicit host sync (``.item()``, ``int()``, ``np.asarray``, ...)
         inside engine/runner hot loops
  JX003  use-after-donation: a name passed at a ``donate_argnums`` position
         of a jitted callable and read afterwards
  JX004  PRNG state reuse: one key consumed twice without split/fold_in
  JX005  dtype drift: ``np.float64``/``np.int64``/builtin dtypes entering
         jitted math under the ``compat.enable_x64`` shim
  JX006  recompilation risk: jitted callables invoked with Python scalars
         or loop variables inside loops
  JX007  nondeterministic host calls (``time``, ``random``) in device-math
         modules
  JX008  unused-reachability: module-level defs/imports nothing references
         (scripts only by default), so shims cannot accrete dead helpers

Suppression: append ``# tpusim-lint: disable=JX002 -- reason`` to the
offending line (or put the comment alone on the line above). A committed
baseline file grandfathers pre-existing findings; the CI gate fails only on
*new* ones. Configuration lives in ``[tool.tpusim-lint]`` in pyproject.toml.
"""

from __future__ import annotations

from .analysis import ModuleAnalysis
from .baseline import Baseline
from .config import LintConfig, load_config
from .findings import Finding
from .rules import ALL_RULES, lint_paths, lint_source

__all__ = [
    "ALL_RULES",
    "Baseline",
    "Finding",
    "LintConfig",
    "ModuleAnalysis",
    "lint_paths",
    "lint_source",
    "load_config",
]
