"""``tpusim lint`` — a project-aware static analyzer for the JAX hygiene
invariants this codebase's three dispatch paths (scan, pallas, pipelined)
depend on but no runtime test can see until they break on hardware.

The failure modes are the ones the TPU Monte-Carlo literature (Ising-on-TPU,
tfp.mcmc on TPU — PAPERS.md) and this repo's own PR history keep rediscovering:
host syncs hidden in hot loops, donated buffers read after the donating call,
tracer-typed Python branches that silently retrace, dtype drift under the x64
compat shim, and recompilation inside dispatch loops. Each is an AST-visible
pattern; catching them at review time is the cheapest correctness tooling we
can add ahead of a TPU-tunnel session.

Rules (see :mod:`tpusim.lint.rules` for the precise semantics):

  JX001  Python ``if``/``while`` on tracer-typed values in jit-reachable code
  JX002  implicit host sync (``.item()``, ``int()``, ``np.asarray``, ...)
         inside engine/runner hot loops
  JX003  use-after-donation: a name passed at a ``donate_argnums`` position
         of a jitted callable and read afterwards
  JX004  PRNG state reuse: one key consumed twice without split/fold_in
  JX005  dtype drift: ``np.float64``/``np.int64``/builtin dtypes entering
         jitted math under the ``compat.enable_x64`` shim
  JX006  recompilation risk: jitted callables invoked with Python scalars
         or loop variables inside loops
  JX007  nondeterministic host calls (``time``, ``random``) in device-math
         modules
  JX008  unused-reachability: module-level defs/imports nothing references
         (scripts only by default), so shims cannot accrete dead helpers

A second, *cross-module* pass (tpusim.lint.contracts) pins the jax-free
orchestration layer's stringly-typed protocols — the failure surface the
telemetry/chaos/fleet/packed PRs grew that no per-module rule can see:

  JX010  telemetry contract: span names / attr keys consumed by the
         dashboards but emitted nowhere; schema-v2 required-row-field
         omissions; raw ``["key"]`` attr subscripts a torn ledger crashes
  JX011  chaos seams: code ``fire()`` sites vs the README seam table vs the
         committed ``drills/*.json`` plans — all three must agree
  JX012  finalize leaf naming: every engine output leaf must self-describe
         its combine_sums merge and its runner strip/checkpoint fate
  JX013  CLI docs drift: a README-documented ``--flag`` no parser declares

A third, whole-project *thread-safety* pass (tpusim.lint.concurrency) gates
the repo's thread populations (fleet heartbeat, chaos watchdog, metrics
HTTP server, bench hard watchdog) ahead of the ``tpusim serve`` daemon:

  JX015  unsynchronized shared state: written in a thread body (or any
         function reachable from one), touched from another context, no
         common lock held at both sites
  JX016  thread lifecycle: non-daemon threads never joined, dropped
         ``start()`` handles, daemon file I/O without the beat-retry
         ``except OSError`` guard
  JX017  inconsistent nested lock ordering across the module set (deadlock)
  JX018  blocking call (device dispatch, subprocess wait, socket accept,
         untimed ``queue.get``) inside a held-lock region
  JX019  fork/subprocess from thread context; non-async-signal-safe work
         in ``signal.signal`` handlers

Suppression: append ``# tpusim-lint: disable=JX002 -- reason`` to the
offending line (or put the comment alone on the line above). A committed
baseline file grandfathers pre-existing findings; the CI gate fails only on
*new* ones. Configuration lives in ``[tool.tpusim-lint]`` in pyproject.toml.
"""

from __future__ import annotations

from .analysis import ModuleAnalysis
from .baseline import Baseline
from .concurrency import CONCURRENCY_RULES, lint_concurrency
from .config import LintConfig, load_config
from .contracts import CONTRACT_RULES, lint_contracts
from .findings import Finding
from .rules import ALL_RULES, lint_paths, lint_source

__all__ = [
    "ALL_RULES",
    "CONCURRENCY_RULES",
    "CONTRACT_RULES",
    "Baseline",
    "Finding",
    "LintConfig",
    "ModuleAnalysis",
    "lint_concurrency",
    "lint_contracts",
    "lint_paths",
    "lint_source",
    "load_config",
]
