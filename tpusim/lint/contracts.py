"""Interprocedural contract pass: the stringly-typed protocols PRs 5-12 grew.

The per-module rules (tpusim.lint.rules) pin JAX/device hygiene; this pass
pins the *jax-free orchestration layer*, which is held together by string
literals no runtime test checks until a dashboard renders "?" or a drill
certifies a seam nothing fires:

  JX010  telemetry span/attr contract — every span name and attr key a
         consumer reads (``attrs.get("...")``, ``sp["span"] == "..."`` in
         report/watch/tracing/convergence/fleet) must be *emitted* somewhere
         (``recorder.emit(...)`` keywords, ``**attrs`` spreads resolved
         through local dict construction and attr-returning helpers);
         schema-v2 required row fields must appear in the writer's row
         literal and in the README schema doc; raw ``["key"]`` subscripts on
         span attrs in consumer modules are the None-intolerance bug class
         a torn/foreign ledger turns into a dashboard crash.
  JX011  chaos seam registry — every ``chaos.fire("seam")`` call site, the
         README seam table and the committed ``drills/*.json`` plans must
         agree: a drill naming a seam no code fires certifies nothing, and
         a fired seam the table omits is an undocumented failure mode.
  JX012  finalize leaf naming contract — every leaf name the engines store
         into a ``run_batch`` output dict must self-describe its merge
         (``tele_``/``stats_``/``flight_`` prefix, ``_sum``/``_max``/
         ``_per_run`` suffix, or the scalar allowlist) so ``combine_sums``
         cannot silently mis-merge it and the runner's strip lists cannot
         leak it into checkpoints; the tele/per-run keys the runner and the
         packed dispatcher read by name must be keys the engines produce.
  JX013  CLI flag docs drift — a ``--flag`` the README (or drills/README)
         documents that no argparse ``add_argument`` declares.

Like the per-module pass, everything here is AST/text only and jax-free.
Unlike it, the pass is *whole-project*: it reads its own configured module
set from the repo root (plus README.md and drills/), so it only runs on the
full-walk CLI invocation — linting one file cannot see a cross-module
contract. Python findings honor the same ``# tpusim-lint: disable=`` comments;
README/drill findings are baseline-only (there is no comment syntax there).
"""

from __future__ import annotations

import ast
import itertools
import json
import re
from pathlib import Path
from typing import Callable, Iterator

from .config import LintConfig
from .findings import Finding, Suppressions

#: Call leaves recognized as span emitters when the first argument is a
#: string constant (TelemetryRecorder.emit, the fleet's _emit wrapper, the
#: recorder's span() context manager).
_EMIT_LEAVES = frozenset({"emit", "_emit", "span"})

#: emit() keyword-only parameters that are row fields, not attrs.
_ROW_KEYWORDS = frozenset({"t_start", "dur_s"})


def _const_str(node: ast.AST) -> str | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _attr_leaf(func: ast.AST) -> str | None:
    if isinstance(func, ast.Attribute):
        return func.attr
    if isinstance(func, ast.Name):
        return func.id
    return None


def scope_nodes(scope: ast.AST):
    """Walk one scope's nodes. For a Module, do NOT descend into function
    bodies: every function is scanned as its own scope, and merging all
    functions' locals into one module-wide namespace would both manufacture
    cross-function false positives (an unrelated function's same-named
    local classified as span attrs) and hide real drift (an unrelated
    local's dict stores inflating the emitted-key set)."""
    if not isinstance(scope, ast.Module):
        yield from ast.walk(scope)
        return
    stack = list(ast.iter_child_nodes(scope))
    while stack:
        node = stack.pop()
        yield node
        if not isinstance(
            node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            stack.extend(ast.iter_child_nodes(node))


# ---------------------------------------------------------------------------
# String-possibility resolution: which constant strings can an expression be?


class StrEnv:
    """Possible constant-string bindings of local names: loop targets over
    constant tuples, dict-literal key sets, and module-level constant tuples
    (resolved across the scanned module set, import-from aliases included)."""

    def __init__(self, module: "ModuleFacts", func: ast.AST):
        self.names: dict[str, set[str]] = {}
        self.module = module
        for node in scope_nodes(func):
            if isinstance(node, (ast.For, ast.AsyncFor)) or isinstance(
                node, ast.comprehension
            ):
                it = node.iter
                targets = node.target
                consts = self._iterable_strings(it)
                if consts is None:
                    continue
                if isinstance(targets, ast.Name):
                    self.names.setdefault(targets.id, set()).update(consts)
                elif isinstance(targets, (ast.Tuple, ast.List)) and targets.elts:
                    # ``for name, _, _ in STATS`` binds the FIRST element;
                    # the module-tuple resolver already projected to it.
                    first = targets.elts[0]
                    if isinstance(first, ast.Name):
                        self.names.setdefault(first.id, set()).update(consts)

    def _iterable_strings(self, it: ast.AST) -> set[str] | None:
        if isinstance(it, (ast.Tuple, ast.List, ast.Set)):
            out = {s for s in (_const_str(e) for e in it.elts) if s is not None}
            return out or None
        if isinstance(it, ast.Call):
            leaf = _attr_leaf(it.func)
            if leaf in ("items", "keys") and isinstance(it.func, ast.Attribute):
                base = it.func.value
                if isinstance(base, ast.Name):
                    keys = self.module.local_dict_keys.get(base.id)
                    if keys:
                        return keys
            return None
        if isinstance(it, ast.Name):
            # Iterating a dict name yields its keys.
            return (
                self.module.resolve_const_tuple(it.id)
                or self.module.local_dict_keys.get(it.id)
            )
        return None

    def possible(self, e: ast.AST) -> set[str] | None:
        """All constant strings ``e`` can evaluate to, or None if open."""
        s = _const_str(e)
        if s is not None:
            return {s}
        if isinstance(e, ast.Name):
            got = self.names.get(e.id)
            if got:
                return got
            return self.module.resolve_const_tuple(e.id)
        if isinstance(e, ast.JoinedStr):
            parts: list[set[str]] = []
            for v in e.values:
                if isinstance(v, ast.Constant):
                    parts.append({str(v.value)})
                elif isinstance(v, ast.FormattedValue):
                    sub = self.possible(v.value)
                    if sub is None:
                        return None
                    parts.append(sub)
                else:
                    return None
            out = {"".join(c) for c in itertools.product(*parts)}
            return out if len(out) <= 64 else None
        if isinstance(e, ast.BinOp) and isinstance(e.op, ast.Add):
            left, right = self.possible(e.left), self.possible(e.right)
            if left is None or right is None:
                return None
            out = {a + b for a in left for b in right}
            return out if len(out) <= 64 else None
        return None


class ModuleFacts:
    """One parsed module plus the cheap global facts the resolvers need."""

    def __init__(self, path: str, source: str):
        self.path = path
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source)
        self.suppressions = Suppressions(source)
        self.suppressions.extend_spans(self.tree)
        #: module-level NAME -> tuple/list of string constants (or of tuples,
        #: projected to their first string element — the STATS shape).
        self.const_tuples: dict[str, set[str]] = {}
        #: module-level NAME -> single string constant.
        self.const_strs: dict[str, str] = {}
        #: import-from aliases: local name -> (module leaf, original name).
        self.imports: dict[str, tuple[str, str]] = {}
        #: function-scope dict literals by name (best effort, last wins) —
        #: the StrEnv ``for k in hist_run`` resolution source.
        self.local_dict_keys: dict[str, set[str]] = {}
        #: all modules, injected by the project pass for import resolution.
        self.project: dict[str, "ModuleFacts"] = {}
        self._collect()

    def _collect(self) -> None:
        for node in self.tree.body:
            if isinstance(node, ast.ImportFrom) and node.module:
                leaf = node.module.rsplit(".", 1)[-1]
                for alias in node.names:
                    self.imports[alias.asname or alias.name] = (leaf, alias.name)
            elif isinstance(node, ast.Assign) and len(node.targets) == 1:
                tgt = node.targets[0]
                if not isinstance(tgt, ast.Name):
                    continue
                s = _const_str(node.value)
                if s is not None:
                    self.const_strs[tgt.id] = s
                elif isinstance(node.value, (ast.Tuple, ast.List)):
                    out: set[str] = set()
                    for e in node.value.elts:
                        s = _const_str(e)
                        if s is not None:
                            out.add(s)
                        elif isinstance(e, (ast.Tuple, ast.List)) and e.elts:
                            s = _const_str(e.elts[0])
                            if s is not None:
                                out.add(s)
                    if out:
                        self.const_tuples[tgt.id] = out
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Dict):
                keys = {
                    s for s in (_const_str(k) for k in node.value.keys if k)
                    if s is not None
                }
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name) and keys:
                        self.local_dict_keys.setdefault(tgt.id, set()).update(keys)

    def resolve_const_tuple(self, name: str) -> set[str] | None:
        if name in self.const_tuples:
            return self.const_tuples[name]
        if name in self.imports:
            mod_leaf, orig = self.imports[name]
            other = self.project.get(mod_leaf)
            if other is not None and orig in other.const_tuples:
                return other.const_tuples[orig]
        return None

    def resolve_const_str(self, name: str) -> str | None:
        if name in self.const_strs:
            return self.const_strs[name]
        if name in self.imports:
            mod_leaf, orig = self.imports[name]
            other = self.project.get(mod_leaf)
            if other is not None:
                return other.const_strs.get(orig)
        return None

    def finding(self, rule: str, node: ast.AST, message: str) -> Finding:
        line = getattr(node, "lineno", 1)
        col = getattr(node, "col_offset", 0)
        text = self.lines[line - 1].strip() if 0 < line <= len(self.lines) else ""
        return Finding(rule, self.path, line, col, message, text)


# ---------------------------------------------------------------------------
# Emitted-side extraction (spans + attr keys), interprocedural.


class EmitExtractor:
    """Union of span names and attr keys any emit site can produce, with
    ``**spread`` arguments resolved through local dict construction
    (literals, ``dict(...)``, subscript stores, ``.update(...)``) and
    through attr-returning helpers by simple name (``environment_attrs``,
    ``memory_attrs``, ``summary_attrs`` — whatever the scanned modules
    define). Over-approximate by design: an extra emitted key only weakens
    JX010, a missed one breaks the dogfood, so unresolvable spreads are
    skipped rather than poisoning the whole span space."""

    def __init__(self, modules: list[ModuleFacts], config: LintConfig):
        self.modules = modules
        self.config = config
        self.spans: set[str] = set()
        self.attr_keys: set[str] = set()
        #: function simple name -> dict keys its returned dicts can carry.
        self._fn_keys: dict[str, set[str]] = {}
        self._fn_defs: dict[str, list[tuple[ModuleFacts, ast.AST]]] = {}
        for m in modules:
            for node in ast.walk(m.tree):
                if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    self._fn_defs.setdefault(node.name, []).append((m, node))
        for m in modules:
            self._scan_module(m)

    # -- helper-function return keys ------------------------------------

    def fn_return_keys(self, name: str, _seen: frozenset = frozenset()) -> set[str]:
        if name in self._fn_keys:
            return self._fn_keys[name]
        if name in _seen:
            return set()
        out: set[str] = set()
        for m, fn in self._fn_defs.get(name, []):
            returned: list[ast.AST] = [
                r.value for r in ast.walk(fn)
                if isinstance(r, ast.Return) and r.value is not None
            ]
            env = StrEnv(m, fn)
            for value in returned:
                out |= self._dict_expr_keys(m, fn, env, value, _seen | {name})
        self._fn_keys[name] = out
        return out

    def _dict_expr_keys(
        self, m: ModuleFacts, scope: ast.AST, env: StrEnv, e: ast.AST,
        _seen: frozenset = frozenset(),
    ) -> set[str]:
        """Keys a dict-valued expression can carry."""
        out: set[str] = set()
        if isinstance(e, ast.Dict):
            for k, v in zip(e.keys, e.values):
                if k is None:  # ``**inner`` inside a literal
                    out |= self._dict_expr_keys(m, scope, env, v, _seen)
                else:
                    ks = env.possible(k)
                    if ks:
                        out |= ks
        elif isinstance(e, ast.Call):
            leaf = _attr_leaf(e.func)
            if leaf == "dict":
                for kw in e.keywords:
                    if kw.arg:
                        out.add(kw.arg)
                    else:
                        out |= self._dict_expr_keys(m, scope, env, kw.value, _seen)
            elif leaf:
                out |= self.fn_return_keys(leaf, _seen)
        elif isinstance(e, ast.Name):
            out |= self._local_dict_keys(m, scope, env, e.id, _seen)
        elif isinstance(e, ast.IfExp):
            out |= self._dict_expr_keys(m, scope, env, e.body, _seen)
            out |= self._dict_expr_keys(m, scope, env, e.orelse, _seen)
        elif isinstance(e, ast.DictComp):
            # ``{k: v for k, v in NAME.items() if ...}`` — the fleet summary
            # re-spread; keys come from the iterated dict.
            it = e.generators[0].iter if e.generators else None
            if isinstance(it, ast.Call) and _attr_leaf(it.func) == "items":
                base = it.func.value  # type: ignore[union-attr]
                if isinstance(base, ast.Name):
                    out |= self._local_dict_keys(m, scope, env, base.id, _seen)
        elif isinstance(e, ast.BoolOp):
            for v in e.values:
                out |= self._dict_expr_keys(m, scope, env, v, _seen)
        return out

    def _local_dict_keys(
        self, m: ModuleFacts, scope: ast.AST, env: StrEnv, name: str,
        _seen: frozenset = frozenset(),
    ) -> set[str]:
        """Keys the local dict ``name`` can hold inside ``scope``: literal/
        dict() assignments, constant subscript stores, and .update() calls."""
        out: set[str] = set()
        for node in scope_nodes(scope):
            if isinstance(node, ast.Assign):
                if any(
                    isinstance(t, ast.Name) and t.id == name for t in node.targets
                ) and not isinstance(node.value, ast.Name):
                    out |= self._dict_expr_keys(m, scope, env, node.value, _seen)
            elif isinstance(node, ast.Subscript) and isinstance(
                node.ctx, ast.Store
            ):
                if isinstance(node.value, ast.Name) and node.value.id == name:
                    ks = env.possible(node.slice)
                    if ks:
                        out |= ks
            elif isinstance(node, ast.Call) and isinstance(
                node.func, ast.Attribute
            ):
                if (
                    node.func.attr == "update"
                    and isinstance(node.func.value, ast.Name)
                    and node.func.value.id == name
                ):
                    for kw in node.keywords:
                        if kw.arg:
                            out.add(kw.arg)
                    for a in node.args:
                        out |= self._dict_expr_keys(m, scope, env, a, _seen)
        return out

    # -- emit-site scan ---------------------------------------------------

    def _scan_module(self, m: ModuleFacts) -> None:
        funcs = [
            n for n in ast.walk(m.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ] + [m.tree]
        for scope in funcs:
            env: StrEnv | None = None
            for node in scope_nodes(scope):
                if not isinstance(node, ast.Call):
                    continue
                leaf = _attr_leaf(node.func)
                if leaf in self.config.context_methods:
                    # CompileLedger.set_context(...) keywords flow into every
                    # later ``compile`` span via ``**self._context``.
                    for kw in node.keywords:
                        if kw.arg:
                            self.attr_keys.add(kw.arg)
                    continue
                if leaf not in _EMIT_LEAVES or not node.args:
                    continue
                span = _const_str(node.args[0])
                if span is None:
                    continue
                self.spans.add(span)
                if env is None:
                    env = StrEnv(m, scope)
                for kw in node.keywords:
                    if kw.arg:
                        if kw.arg not in _ROW_KEYWORDS:
                            self.attr_keys.add(kw.arg)
                    else:
                        self.attr_keys |= self._dict_expr_keys(
                            m, scope, env, kw.value
                        )


# ---------------------------------------------------------------------------
# Consumed-side extraction: an abstract classifier anchored on the literal
# "attrs"/"span" row fields.

_ATTRS = "attrs"
_ATTRS_COLL = "attrs_coll"
_SPAN = "span_name"
_SPAN_COLL = "span_coll"
_SPAN_KEYED = "span_keyed"


class ConsumeExtractor:
    """Span names and attr keys one module's dashboards *read*.

    The anchor is structural, not nominal: any ``X.get("attrs")`` /
    ``X["attrs"]`` read marks a span-attrs value, any ``X["span"]`` /
    ``X.get("span")`` a span name — then a small fixpoint propagates those
    classifications through local assignment, ``or {}`` defaulting,
    comprehensions, collections and span-keyed dicts
    (``by.setdefault(sp["span"], [])``). Nested payloads (the per-stat
    entries under a ``stats`` attr) are deliberately out of scope: they are
    one more level of protocol than the emit side can resolve, and flagging
    them would be noise, not teeth."""

    def __init__(self, m: ModuleFacts):
        self.m = m
        #: (key, node) consumed attr keys.
        self.attr_reads: list[tuple[str, ast.AST]] = []
        #: (name, node) consumed span names.
        self.span_reads: list[tuple[str, ast.AST]] = []
        #: (prefix, node) consumed span-name prefixes (.startswith).
        self.span_prefixes: list[tuple[str, ast.AST]] = []
        #: raw ``[...]`` subscript reads on attrs values (None-intolerant).
        self.raw_subscripts: list[tuple[str, ast.AST]] = []
        funcs = [
            n for n in ast.walk(m.tree)
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
        ] + [m.tree]
        for scope in funcs:
            self._scan_scope(scope)

    # -- classification ----------------------------------------------------

    def _classify(self, e: ast.AST, names: dict[str, set[str]]) -> set[str]:
        if isinstance(e, ast.Name) and isinstance(e.ctx, ast.Load):
            return names.get(e.id, set())
        if isinstance(e, ast.Call):
            leaf = _attr_leaf(e.func)
            if leaf == "get" and isinstance(e.func, ast.Attribute) and e.args:
                key = _const_str(e.args[0])
                base = self._classify(e.func.value, names)
                if key == "attrs":
                    return {_ATTRS}
                if key == "span":
                    return {_SPAN}
                if _ATTRS_COLL in base:
                    return {_ATTRS}
                return set()
            if leaf in ("str",) and len(e.args) == 1:
                return self._classify(e.args[0], names) & {_SPAN}
            if leaf in ("list", "sorted", "set", "tuple") and e.args:
                return self._classify(e.args[0], names) & {
                    _ATTRS_COLL, _SPAN_COLL
                }
            return set()
        if isinstance(e, ast.Subscript) and isinstance(e.ctx, ast.Load):
            key = _const_str(e.slice)
            base = self._classify(e.value, names)
            if key == "attrs":
                return {_ATTRS}
            if key == "span":
                return {_SPAN}
            if _ATTRS_COLL in base:
                return {_ATTRS}
            return set()
        if isinstance(e, ast.BoolOp):
            out: set[str] = set()
            for v in e.values:
                out |= self._classify(v, names)
            return out
        if isinstance(e, ast.IfExp):
            return self._classify(e.body, names) | self._classify(
                e.orelse, names
            )
        if isinstance(e, (ast.ListComp, ast.GeneratorExp, ast.SetComp)):
            elt = self._classify(e.elt, names)
            out = set()
            if _ATTRS in elt:
                out.add(_ATTRS_COLL)
            if _SPAN in elt:
                out.add(_SPAN_COLL)
            return out
        if isinstance(e, ast.DictComp):
            if _ATTRS in self._classify(e.value, names):
                return {_ATTRS_COLL}
            return set()
        return set()

    # -- fixpoint over one scope -------------------------------------------

    def _scan_scope(self, scope: ast.AST) -> None:
        names: dict[str, set[str]] = {}

        def bind(n: str, kinds: set[str]) -> bool:
            if not kinds:
                return False
            cur = names.setdefault(n, set())
            if kinds - cur:
                cur |= kinds
                return True
            return False

        changed = True
        while changed:
            changed = False
            for node in scope_nodes(scope):
                if isinstance(node, (ast.Assign, ast.AnnAssign, ast.NamedExpr)):
                    value = getattr(node, "value", None)
                    if value is None:
                        continue
                    kinds = self._classify(value, names)
                    targets = (
                        node.targets if isinstance(node, ast.Assign)
                        else [node.target]
                    )
                    for t in targets:
                        if isinstance(t, ast.Name):
                            changed |= bind(t.id, kinds)
                        elif isinstance(t, ast.Subscript) and isinstance(
                            t.value, ast.Name
                        ):
                            # ``latest[pt] = attrs`` / ``by[sp["span"]] = x``
                            if _ATTRS in kinds:
                                changed |= bind(t.value.id, {_ATTRS_COLL})
                            if _SPAN in self._classify(t.slice, names):
                                changed |= bind(t.value.id, {_SPAN_KEYED})
                elif isinstance(node, (ast.For, ast.AsyncFor)):
                    kinds = self._classify(node.iter, names)
                    tgt_kinds: set[str] = set()
                    if _ATTRS_COLL in kinds:
                        tgt_kinds.add(_ATTRS)
                    if _SPAN_COLL in kinds:
                        tgt_kinds.add(_SPAN)
                    if isinstance(node.target, ast.Name):
                        changed |= bind(node.target.id, tgt_kinds)
                elif isinstance(node, ast.comprehension):
                    kinds = self._classify(node.iter, names)
                    tgt_kinds = set()
                    if _ATTRS_COLL in kinds:
                        tgt_kinds.add(_ATTRS)
                    if _SPAN_COLL in kinds:
                        tgt_kinds.add(_SPAN)
                    if isinstance(node.target, ast.Name):
                        changed |= bind(node.target.id, tgt_kinds)
                elif isinstance(node, ast.Call) and isinstance(
                    node.func, ast.Attribute
                ):
                    if (
                        node.func.attr == "setdefault"
                        and isinstance(node.func.value, ast.Name)
                        and node.args
                        and _SPAN in self._classify(node.args[0], names)
                    ):
                        changed |= bind(node.func.value.id, {_SPAN_KEYED})

        env = StrEnv(self.m, scope)
        for node in scope_nodes(scope):
            self._collect_reads(node, names, env)

    def _collect_reads(
        self, node: ast.AST, names: dict[str, set[str]], env: StrEnv
    ) -> None:
        if isinstance(node, ast.Call) and isinstance(node.func, ast.Attribute):
            leaf = node.func.attr
            base = self._classify(node.func.value, names)
            if leaf == "get" and node.args:
                key = _const_str(node.args[0])
                if _ATTRS in base:
                    keys = env.possible(node.args[0])
                    for k in keys or ():
                        self.attr_reads.append((k, node))
                elif _SPAN_KEYED in base and key is not None:
                    self.span_reads.append((key, node))
                # also the DEFAULT expression can consume: a.get("x", a.get("y"))
                # is walked on its own by ast.walk.
            elif leaf == "startswith" and node.args and _SPAN in base:
                pref = _const_str(node.args[0])
                if pref is not None:
                    self.span_prefixes.append((pref, node))
            elif leaf == "pop" and node.args and _ATTRS in base:
                keys = env.possible(node.args[0])
                for k in keys or ():
                    self.attr_reads.append((k, node))
        elif isinstance(node, ast.Subscript) and isinstance(node.ctx, ast.Load):
            base = self._classify(node.value, names)
            key = _const_str(node.slice)
            if _ATTRS in base:
                keys = env.possible(node.slice)
                for k in keys or ():
                    self.attr_reads.append((k, node))
                label = key if key is not None else "<dynamic>"
                self.raw_subscripts.append((label, node))
            elif _SPAN_KEYED in base and key is not None:
                self.span_reads.append((key, node))
        elif isinstance(node, ast.Compare):
            sides = [node.left, *node.comparators]
            classes = [self._classify(s, names) for s in sides]
            for i, op in enumerate(node.ops):
                a, b = sides[i], sides[i + 1]
                ca, cb = classes[i], classes[i + 1]
                if isinstance(op, (ast.Eq, ast.NotEq)):
                    for expr, cls, other in ((a, ca, b), (b, cb, a)):
                        if _SPAN in cls:
                            s = _const_str(other)
                            if s is not None:
                                self.span_reads.append((s, node))
                elif isinstance(op, (ast.In, ast.NotIn)):
                    # "x" in span-coll / span-keyed, or span-name in ("a","b"),
                    # or "key" in attrs.
                    s = _const_str(a)
                    if s is not None and (
                        {_SPAN_COLL, _SPAN_KEYED} & cb
                    ):
                        self.span_reads.append((s, node))
                    elif s is not None and _ATTRS in cb:
                        self.attr_reads.append((s, node))
                    elif _SPAN in ca and isinstance(
                        b, (ast.Tuple, ast.List, ast.Set)
                    ):
                        for e in b.elts:
                            s = _const_str(e)
                            if s is not None:
                                self.span_reads.append((s, node))


# ---------------------------------------------------------------------------
# Project context: parse everything once, run the four rules.


class ProjectContracts:
    def __init__(self, root: Path, config: LintConfig):
        self.root = Path(root)
        self.config = config
        self.modules: dict[str, ModuleFacts] = {}
        self._docs: dict[str, list[str]] = {}
        self._emits: EmitExtractor | None = None
        for rel in config.telemetry_modules:
            self._load(rel)

    @property
    def emits(self) -> "EmitExtractor":
        # Lazy: only the JX010 check reads the emitted-side extraction, and
        # a `--rules JX011` invocation should not pay the interprocedural
        # spread/helper fixpoints over 13 modules for nothing.
        if self._emits is None:
            self._emits = EmitExtractor(
                [self.modules[r] for r in self.config.telemetry_modules
                 if r in self.modules],
                self.config,
            )
        return self._emits

    def _load(self, rel: str) -> ModuleFacts | None:
        if rel in self.modules:
            return self.modules[rel]
        p = self.root / rel
        if not p.exists():
            return None
        try:
            facts = ModuleFacts(rel, p.read_text())
        except SyntaxError:
            return None
        self.modules[rel] = facts
        # Import resolution is by module *leaf* name (convergence.STATS).
        for m in self.modules.values():
            m.project[Path(rel).stem] = facts
            facts.project[Path(m.path).stem] = m
        return facts

    def _doc_lines(self, rel: str) -> list[str]:
        # Memoized like the Python-module cache: the rules re-anchor finding
        # text per doc finding, and an N-row drift must not re-read the
        # whole README N times.
        cached = self._docs.get(rel)
        if cached is not None:
            return cached
        p = self.root / rel
        lines = p.read_text().splitlines() if p.exists() else []
        self._docs[rel] = lines
        return lines

    def _doc_finding(
        self, rule: str, rel: str, lineno: int, message: str,
        lines: list[str], col: int = 0,
    ) -> Finding:
        text = lines[lineno - 1].strip() if 0 < lineno <= len(lines) else ""
        return Finding(rule, rel, lineno, col, message, text)

    # -- JX010 -------------------------------------------------------------

    def check_telemetry(self) -> Iterator[Finding]:
        emitted_spans = self.emits.spans
        emitted_keys = self.emits.attr_keys
        for rel in self.config.telemetry_modules:
            m = self.modules.get(rel)
            if m is None:
                continue
            cons = ConsumeExtractor(m)
            for key, node in cons.attr_reads:
                if key not in emitted_keys:
                    yield m.finding(
                        "JX010", node,
                        f"span attr `{key}` is consumed here but no emit "
                        f"site in the telemetry modules ever produces it — "
                        f"a renamed or dropped producer key renders this "
                        f"panel as permanent n/a",
                    )
            for name, node in cons.span_reads:
                if name not in emitted_spans:
                    yield m.finding(
                        "JX010", node,
                        f"span name `{name}` is consumed here but never "
                        f"emitted by any producer — dead dashboard branch "
                        f"or renamed span",
                    )
            for pref, node in cons.span_prefixes:
                if not any(s.startswith(pref) for s in emitted_spans):
                    yield m.finding(
                        "JX010", node,
                        f"span-name prefix `{pref}` matches no emitted span",
                    )
            for label, node in cons.raw_subscripts:
                yield m.finding(
                    "JX010", node,
                    f"raw `[{label!r}]` subscript on span attrs — a torn or "
                    f"foreign ledger row raises KeyError/TypeError in the "
                    f"dashboard; use `.get()` with a None-tolerant default",
                )
        yield from self._check_schema()

    def _check_schema(self) -> Iterator[Finding]:
        writer = self.config.span_writer
        required = set(self.config.span_schema_required)
        if not writer or not required:
            return
        rel, _, qual = writer.partition(":")
        m = self._load(rel)
        if m is None:
            return
        parts = qual.split(".")
        node: ast.AST | None = m.tree
        for part in parts:
            found = None
            for child in ast.walk(node):  # type: ignore[arg-type]
                if isinstance(
                    child, (ast.ClassDef, ast.FunctionDef, ast.AsyncFunctionDef)
                ) and child.name == part:
                    found = child
                    break
            node = found
            if node is None:
                break
        if node is None:
            yield self._doc_finding(
                "JX010", rel, 1,
                f"span writer `{qual}` not found in {rel} — the schema "
                f"contract check has nothing to pin (config drift)",
                m.lines,
            )
            return
        row_keys: set[str] = set()
        for sub in ast.walk(node):
            if isinstance(sub, ast.Dict):
                for k in sub.keys:
                    s = _const_str(k) if k is not None else None
                    if s is not None:
                        row_keys.add(s)
        missing = sorted(required - row_keys)
        if missing:
            yield m.finding(
                "JX010", node,
                f"span row literal in `{qual}` omits required schema-v2 "
                f"field(s) {missing} — consumers treat these as the row "
                f"contract (config span-schema-required)",
            )
        # README schema doc cross-check, marker-anchored. A missing marker
        # is itself a finding: an uncheckable schema doc rots silently.
        saw_marker = False
        for doc in self.config.doc_files:
            lines = self._doc_lines(doc)
            for i, line in enumerate(lines, start=1):
                if "tpusim-lint: span-schema" not in line:
                    continue
                saw_marker = True
                blob = " ".join(lines[i:i + 6])
                mjson = re.search(r"\{[^}]*\}", blob)
                doc_fields = set(re.findall(r'"([a-z_]+)"', mjson.group(0))) \
                    if mjson else set()
                for f in sorted(required - doc_fields):
                    yield self._doc_finding(
                        "JX010", doc, i,
                        f"span-schema doc omits required field `{f}` "
                        f"(schema v2; the row literal in {rel} is the "
                        f"source of truth)",
                        lines,
                    )
                for f in sorted(doc_fields - row_keys):
                    yield self._doc_finding(
                        "JX010", doc, i,
                        f"span-schema doc lists `{f}` which the writer's "
                        f"row literal never produces",
                        lines,
                    )
        if not saw_marker and self.config.doc_files:
            doc = self.config.doc_files[0]
            yield self._doc_finding(
                "JX010", doc, 1,
                "no `tpusim-lint: span-schema` marker found in the doc "
                "files — the span-schema doc cannot be cross-checked (add "
                "the marker comment above the schema line)",
                self._doc_lines(doc),
            )

    # -- JX011 -------------------------------------------------------------

    def _fired_seams(self) -> dict[str, tuple[ModuleFacts, ast.AST]]:
        fired: dict[str, tuple[ModuleFacts, ast.AST]] = {}
        for rel in self._include_files():
            m = self._load(rel)
            if m is None:
                continue
            for node in ast.walk(m.tree):
                if (
                    isinstance(node, ast.Call)
                    and _attr_leaf(node.func) == "fire"
                    and node.args
                ):
                    seam = _const_str(node.args[0])
                    if seam is not None:
                        fired.setdefault(seam, (m, node))
        return fired

    def _include_files(self) -> list[str]:
        out = []
        for pattern in self.config.include:
            for p in sorted(self.root.glob(pattern)):
                rel = p.relative_to(self.root).as_posix()
                if self.config.is_included(rel) and rel not in out:
                    out.append(rel)
        return out

    def _readme_seams(self) -> tuple[dict[str, tuple[str, int]], bool]:
        """Seam names from the marker-anchored README table:
        name -> (doc path, line)."""
        seams: dict[str, tuple[str, int]] = {}
        saw_marker = False
        for doc in self.config.doc_files:
            lines = self._doc_lines(doc)
            armed = in_table = False
            for i, line in enumerate(lines, start=1):
                if "tpusim-lint: chaos-seam-table" in line:
                    saw_marker = armed = True
                    continue
                is_row = line.lstrip().startswith("|")
                if armed and is_row:
                    armed, in_table = False, True
                if in_table:
                    mrow = re.match(r"\s*\|\s*`([A-Za-z0-9_.]+)`\s*\|", line)
                    if mrow:
                        seams.setdefault(mrow.group(1), (doc, i))
                    elif not is_row:
                        in_table = False
        return seams, saw_marker

    def check_chaos_seams(self) -> Iterator[Finding]:
        fired = self._fired_seams()
        documented, saw_marker = self._readme_seams()
        if not saw_marker and self.config.doc_files:
            doc = self.config.doc_files[0]
            yield self._doc_finding(
                "JX011", doc, 1,
                "no `tpusim-lint: chaos-seam-table` marker found in the doc "
                "files — the seam table cannot be cross-checked (add the "
                "marker comment above the fault-point table)",
                self._doc_lines(doc),
            )
        for seam, (doc, line) in sorted(documented.items()):
            if seam not in fired:
                yield self._doc_finding(
                    "JX011", doc, line,
                    f"documented chaos seam `{seam}` is fired by no "
                    f"`chaos.fire(...)` call site — stale table row or "
                    f"renamed seam",
                    self._doc_lines(doc),
                )
        for seam, (m, node) in sorted(fired.items()):
            if saw_marker and seam not in documented:
                yield m.finding(
                    "JX011", node,
                    f"chaos seam `{seam}` is fired here but missing from "
                    f"the documented seam table — an undocumented failure "
                    f"mode no drill can target by contract",
                )
        for pattern in self.config.drill_globs:
            for p in sorted(self.root.glob(pattern)):
                rel = p.relative_to(self.root).as_posix()
                try:
                    text = p.read_text()
                    plan = json.loads(text)
                except (OSError, json.JSONDecodeError):
                    plan = None
                # Valid JSON of the wrong SHAPE (a top-level list, a string
                # fault entry) is just as broken as unparseable JSON — and
                # must be a finding, not an analyzer AttributeError.
                faults = plan.get("faults", []) if isinstance(plan, dict) else None
                if not isinstance(faults, list) or not all(
                    isinstance(f, dict) for f in faults
                ):
                    yield Finding(
                        "JX011", rel, 1, 0,
                        "drill plan is unreadable/unparseable (not a "
                        '{"faults": [{...}]} object) — a broken committed '
                        "drill certifies nothing",
                    )
                    continue
                lines = text.splitlines()
                for fault in faults:
                    point = fault.get("point")
                    if not isinstance(point, str) or point in fired:
                        continue
                    lineno = next(
                        (i for i, ln in enumerate(lines, start=1)
                         if f'"{point}"' in ln), 1,
                    )
                    yield self._doc_finding(
                        "JX011", rel, lineno,
                        f"drill names seam `{point}` which no code fires — "
                        f"the drill can never inject and silently certifies "
                        f"an undrilled recovery path",
                        lines,
                    )

    # -- JX012 -------------------------------------------------------------

    def _leaf_stores(self) -> dict[str, tuple[ModuleFacts, ast.AST]]:
        stores: dict[str, tuple[ModuleFacts, ast.AST]] = {}
        dict_names = set(self.config.leaf_dict_names)
        for rel in self.config.engine_leaf_modules:
            m = self._load(rel)
            if m is None:
                continue
            funcs = [
                n for n in ast.walk(m.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ]
            for fn in funcs:
                env = StrEnv(m, fn)
                for node in ast.walk(fn):
                    if (
                        isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Store)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in dict_names
                    ):
                        for k in env.possible(node.slice) or ():
                            stores.setdefault(k, (m, node))
                    elif (
                        isinstance(node, ast.Return)
                        and isinstance(node.value, ast.Dict)
                        and "finalize" in fn.name
                    ):
                        for k in node.value.keys:
                            s = _const_str(k) if k is not None else None
                            if s is not None:
                                stores.setdefault(s, (m, node))
            # dict literals ASSIGNED to the configured names
            # (loop_out_specs = {...}) carry leaf keys too.
            for node in ast.walk(m.tree):
                if isinstance(node, ast.Assign) and isinstance(
                    node.value, ast.Dict
                ):
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name) and tgt.id in dict_names:
                            for k in node.value.keys:
                                s = _const_str(k) if k is not None else None
                                if s is not None:
                                    stores.setdefault(s, (m, node))
        return stores

    def _leaf_class(self, leaf: str) -> bool:
        c = self.config
        return (
            leaf.startswith(tuple(c.leaf_strip_prefixes))
            or leaf.endswith(tuple(c.leaf_merge_suffixes))
            or leaf in c.leaf_scalar_allowlist
        )

    def check_finalize_leaves(self) -> Iterator[Finding]:
        stores = self._leaf_stores()
        c = self.config
        # (1) Naming-contract: every stored leaf self-describes its merge.
        for leaf, (m, node) in sorted(stores.items()):
            if not self._leaf_class(leaf):
                yield m.finding(
                    "JX012", node,
                    f"finalize leaf `{leaf}` matches no merge class "
                    f"(prefixes {sorted(c.leaf_strip_prefixes)}, suffixes "
                    f"{sorted(c.leaf_merge_suffixes)}, scalars "
                    f"{sorted(c.leaf_scalar_allowlist)}) — combine_sums "
                    f"would silently ADD it and the runner would checkpoint "
                    f"it; name the merge semantics into the leaf",
                )
        # (2) combine_sums must implement the configured merge literals.
        engine_rel = c.engine_leaf_modules[0] if c.engine_leaf_modules else None
        if engine_rel:
            m = self.modules.get(engine_rel) or self._load(engine_rel)
            if m is not None:
                fn = next(
                    (n for n in ast.walk(m.tree)
                     if isinstance(n, ast.FunctionDef)
                     and n.name == "combine_sums"), None,
                )
                if fn is None:
                    yield Finding(
                        "JX012", engine_rel, 1, 0,
                        "combine_sums not found — the merge-rule contract "
                        "check has nothing to pin",
                    )
                else:
                    lits: set[str] = set()
                    for node in ast.walk(fn):
                        if (
                            isinstance(node, ast.Call)
                            and _attr_leaf(node.func)
                            in ("startswith", "endswith")
                            and node.args
                        ):
                            s = _const_str(node.args[0])
                            if s is None and isinstance(node.args[0], ast.Name):
                                s = m.resolve_const_str(node.args[0].id)
                            if s is not None:
                                lits.add(s)
                    for miss in sorted(set(c.combine_merge_literals) - lits):
                        yield m.finding(
                            "JX012", fn,
                            f"combine_sums no longer tests the merge-rule "
                            f"literal `{miss}` the leaf contract declares — "
                            f"leaves of that class would fall through to "
                            f"the additive default",
                        )
        # (3) Runner strip list covers every telemetry prefix.
        strip_rel = c.leaf_consumer_modules[0] if c.leaf_consumer_modules else None
        if strip_rel:
            m = self._load(strip_rel)
            if m is not None:
                strip_lits: set[str] = set()
                for node in ast.walk(m.tree):
                    if (
                        isinstance(node, ast.Call)
                        and _attr_leaf(node.func) == "startswith"
                        and node.args
                    ):
                        s = _const_str(node.args[0])
                        if s is not None:
                            strip_lits.add(s)
                for pref in sorted(set(c.leaf_strip_prefixes) - strip_lits):
                    yield Finding(
                        "JX012", strip_rel, 1, 0,
                        f"runner never strips the `{pref}` telemetry prefix "
                        f"(no startswith literal) — leaves of that class "
                        f"would leak into the stat/checkpoint path",
                    )
        # (4) Consumed leaf keys must be produced — scoped to the dict
        # receivers that hold engine run_batch outputs (leaf-read-names), so
        # summary/config dicts that reuse a leaf-ish suffix stay out.
        produced = set(stores)
        read_names = set(c.leaf_read_names)
        for rel in c.leaf_consumer_modules:
            m = self._load(rel)
            if m is None:
                continue
            funcs = [
                n for n in ast.walk(m.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ] + [m.tree]
            for fn in funcs:
                env = StrEnv(m, fn)

                def key_strings(e: ast.AST) -> set[str]:
                    # Constant keys and f-string/concat patterns only: a bare
                    # Name key is generic dict iteration (the strip
                    # comprehensions), not a named leaf read — and StrEnv's
                    # function-wide merge of same-named loop targets would
                    # mis-resolve it.
                    if isinstance(e, ast.Name):
                        return set()
                    return env.possible(e) or set()

                for node in scope_nodes(fn):
                    keys: set[str] = set()
                    if (
                        isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Load)
                        and isinstance(node.value, ast.Name)
                        and node.value.id in read_names
                    ):
                        keys = key_strings(node.slice)
                    elif (
                        isinstance(node, ast.Call)
                        and _attr_leaf(node.func) in ("get", "pop")
                        and isinstance(node.func, ast.Attribute)
                        and isinstance(node.func.value, ast.Name)
                        and node.func.value.id in read_names
                        and node.args
                    ):
                        keys = key_strings(node.args[0])
                    for k in keys - produced:
                        yield m.finding(
                            "JX012", node,
                            f"leaf key `{k}` is read from an engine output "
                            f"dict here but no engine finalize/aux path "
                            f"produces it — renamed counter or dead consumer",
                        )
        # (5) Packed per-run leaves must declare a piece-boundary fate. Every
        # `*_per_run` / `flight_*` leaf an engine stores rides the packed
        # runs-axis, so the packed orchestrators must either slice it per
        # point at piece boundaries (a constant-key read in one of the
        # packed-consumer modules) or the config must list it in
        # packed-leaf-strip as intentionally dropped. A leaf with neither
        # fate would vanish silently from packed grid results while surviving
        # the sequential path — exactly the class of drift the packed
        # completion removed.
        packed_leaves = {
            leaf for leaf in stores
            if leaf.endswith("_per_run") or leaf.startswith("flight_")
        }
        packed_reads: set[str] = set()
        for rel in c.packed_consumer_modules:
            m = self._load(rel)
            if m is None:
                continue
            funcs = [
                n for n in ast.walk(m.tree)
                if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef))
            ] + [m.tree]
            for fn in funcs:
                env = StrEnv(m, fn)
                for node in scope_nodes(fn):
                    # Receiver-agnostic on purpose: the packed modules slice
                    # these leaves out of several locally-named dicts (raw,
                    # sums, piece views), and a false "read" here only
                    # suppresses a finding — the naming contract in (1)
                    # still covers the leaf itself.
                    expr = None
                    if (
                        isinstance(node, ast.Subscript)
                        and isinstance(node.ctx, ast.Load)
                        and not isinstance(node.slice, ast.Name)
                    ):
                        expr = node.slice
                    elif (
                        isinstance(node, ast.Call)
                        and _attr_leaf(node.func) in ("get", "pop")
                        and node.args
                        and not isinstance(node.args[0], ast.Name)
                    ):
                        expr = node.args[0]
                    if expr is not None:
                        packed_reads |= env.possible(expr) or set()
        for leaf in sorted(
            packed_leaves - packed_reads - set(c.packed_leaf_strip)
        ):
            m, node = stores[leaf]
            yield m.finding(
                "JX012", node,
                f"packed leaf `{leaf}` declares no piece-boundary fate — no "
                f"packed-consumer module ({sorted(c.packed_consumer_modules)})"
                f" reads it by constant name and packed-leaf-strip does not "
                f"list it; a packed grid would drop it silently while the "
                f"sequential path keeps it",
            )

    # -- JX013 -------------------------------------------------------------

    def _declared_flags(self) -> set[str]:
        flags: set[str] = set()
        files: list[str] = []
        for entry in self.config.cli_modules:
            if any(ch in entry for ch in "*?["):
                for p in sorted(self.root.glob(entry)):
                    files.append(p.relative_to(self.root).as_posix())
            else:
                files.append(entry)
        for rel in files:
            m = self._load(rel)
            if m is None:
                continue
            for node in ast.walk(m.tree):
                if (
                    isinstance(node, ast.Call)
                    and _attr_leaf(node.func) == "add_argument"
                ):
                    for a in node.args:
                        s = _const_str(a)
                        if s is not None and s.startswith("--"):
                            flags.add(s)
        return flags

    def check_cli_flags(self) -> Iterator[Finding]:
        declared = self._declared_flags()
        if not declared:
            yield Finding(
                "JX013", self.config.cli_modules[0] if self.config.cli_modules
                else "pyproject.toml", 1, 0,
                "no declared CLI flags found in the configured cli-modules — "
                "the docs-drift check has nothing to compare (config drift)",
            )
            return
        ignore = set(self.config.flag_ignore)
        flag_re = re.compile(r"(?<![\w/=-])(--[a-z][a-z0-9-]*)")
        for doc in self.config.doc_files:
            lines = self._doc_lines(doc)
            for i, line in enumerate(lines, start=1):
                for mflag in flag_re.finditer(line):
                    flag = mflag.group(1)
                    if flag in declared or flag in ignore:
                        continue
                    yield self._doc_finding(
                        "JX013", doc, i,
                        f"documented flag `{flag}` is declared by no "
                        f"argparse parser in the cli-modules — docs drift "
                        f"(or add it to the flag-ignore config for an "
                        f"external tool's flag)",
                        lines, col=mflag.start(1),
                    )

    # -- JX014 -------------------------------------------------------------
    # The metrics/SLO contract: the metrics module's METRICS tuple-of-tuples
    # literal is the exported-family source of truth. Every metric an SLO
    # config references must be in it (a typo'd objective is a permanent
    # rc-2 dead gate), and it must stay in lockstep with the marker-anchored
    # README metrics table — both directions, like the chaos seam table.

    def _metrics_registry(
        self,
    ) -> tuple[ModuleFacts, dict[str, int] | None] | None:
        """(module facts, family name -> registry-element line) from the
        metrics module's ``METRICS`` literal; None when the module itself is
        missing/unparseable, (facts, None) when the literal is."""
        m = self._load(self.config.metrics_module)
        if m is None:
            return None
        for node in m.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == "METRICS"
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                out: dict[str, int] = {}
                for e in node.value.elts:
                    if isinstance(e, (ast.Tuple, ast.List)) and e.elts:
                        s = _const_str(e.elts[0])
                        if s is not None:
                            out.setdefault(s, e.lineno)
                return m, (out or None)
        return m, None

    def _slo_metric_refs(self, rel: str) -> list[tuple[str, int]] | None:
        """(metric name, line) pairs one SLO config references — JSON
        ``{"objectives": [...]}`` or TOML ``[tool.tpusim-slo]`` — or None
        when the file is missing/unparseable/objective-less (structural:
        the runtime gate would exit 2 on the same config)."""
        p = self.root / rel
        try:
            text = p.read_text()
        except OSError:
            return None
        names: list[str] | None = None
        if rel.endswith(".json"):
            try:
                data = json.loads(text)
            except json.JSONDecodeError:
                return None
            rows = data.get("objectives") if isinstance(data, dict) else None
        else:
            from .config import _toml

            if _toml is None:
                rows = None  # regex fallback keeps the gate armed
            else:
                try:
                    data = _toml.loads(text)
                except (ValueError, TypeError):
                    return None
                rows = (
                    data.get("tool", {}).get("tpusim-slo", {}).get("objectives")
                )
        lines = text.splitlines()
        if rows is None and not rel.endswith(".json"):
            out = [
                (mm.group(1), i)
                for i, line in enumerate(lines, start=1)
                for mm in [re.match(
                    r'\s*(?:"metric"\s*:|metric\s*=)\s*"([^"]+)"', line
                )]
                if mm
            ]
            return out or None
        if not isinstance(rows, list) or not rows:
            return None
        names = [r.get("metric") for r in rows if isinstance(r, dict)]
        if not names or not all(isinstance(n, str) for n in names):
            return None
        out = []
        for name in names:
            lineno = next(
                (i for i, line in enumerate(lines, start=1)
                 if f'"{name}"' in line),
                1,
            )
            out.append((name, lineno))
        return out

    def _readme_metrics(self) -> tuple[dict[str, tuple[str, int]], bool]:
        """Metric families from the marker-anchored README metrics table:
        name -> (doc path, line). Same state machine as the seam table."""
        metrics: dict[str, tuple[str, int]] = {}
        saw_marker = False
        for doc in self.config.doc_files:
            lines = self._doc_lines(doc)
            armed = in_table = False
            for i, line in enumerate(lines, start=1):
                if "tpusim-lint: metrics-table" in line:
                    saw_marker = armed = True
                    continue
                is_row = line.lstrip().startswith("|")
                if armed and is_row:
                    armed, in_table = False, True
                if in_table:
                    mrow = re.match(r"\s*\|\s*`([A-Za-z0-9_.]+)`\s*\|", line)
                    if mrow:
                        metrics.setdefault(mrow.group(1), (doc, i))
                    elif not is_row:
                        in_table = False
        return metrics, saw_marker

    def check_metrics_contract(self) -> Iterator[Finding]:
        rel = self.config.metrics_module
        if not rel:
            return
        reg = self._metrics_registry()
        if reg is None:
            yield Finding(
                "JX014", rel, 1, 0,
                "configured metrics-module is missing or unparseable — the "
                "metrics/SLO contract has no registry to pin (config drift)",
            )
            return
        m, families = reg
        if families is None:
            yield m.finding(
                "JX014", m.tree,
                "no module-level METRICS tuple-of-tuples literal found — "
                "the exported metric-family universe must be statically "
                "readable for the SLO/README cross-check",
            )
            return
        # Direction 1: every SLO-config metric must be a registered family
        # (an unregistered objective is a permanent no-data rc-2 dead gate).
        for cfg_rel in self.config.slo_config_files:
            refs = self._slo_metric_refs(cfg_rel)
            if refs is None:
                yield Finding(
                    "JX014", cfg_rel, 1, 0,
                    "SLO config is missing, unparseable, or declares no "
                    "objectives with string `metric` fields — `tpusim slo "
                    "check` would exit 2 on it (dead gate)",
                )
                continue
            cfg_lines = self._doc_lines(cfg_rel)
            for name, line in refs:
                if name not in families:
                    yield self._doc_finding(
                        "JX014", cfg_rel, line,
                        f"SLO objective references metric `{name}` which the "
                        f"metrics registry ({rel}) never emits — the "
                        f"objective can only ever evaluate to no-data "
                        f"(rc 2), never pass",
                        cfg_lines,
                    )
        # Direction 2: registry <-> README metrics table, both ways.
        documented, saw_marker = self._readme_metrics()
        if not saw_marker:
            if self.config.doc_files:
                doc = self.config.doc_files[0]
                yield self._doc_finding(
                    "JX014", doc, 1,
                    "no `tpusim-lint: metrics-table` marker found in the doc "
                    "files — the metrics table cannot be cross-checked (add "
                    "the marker comment above the metric-family table)",
                    self._doc_lines(doc),
                )
            return
        for fam, line in sorted(families.items()):
            if fam not in documented:
                text = m.lines[line - 1].strip() if 0 < line <= len(m.lines) else ""
                yield Finding(
                    "JX014", m.path, line, 0,
                    f"registry metric `{fam}` is missing from the documented "
                    f"metrics table — an undocumented family no scrape "
                    f"consumer can discover by contract",
                    text,
                )
        for fam, (doc, line) in sorted(documented.items()):
            if fam not in families:
                yield self._doc_finding(
                    "JX014", doc, line,
                    f"documented metric `{fam}` is emitted by no registry "
                    f"family in {rel} — stale table row or renamed metric",
                    self._doc_lines(doc),
                )

    # -- JX020 -------------------------------------------------------------
    # The provenance contract: the provenance module's KINDS tuple-of-tuples
    # literal is the artifact-kind source of truth. Every emit_lineage("...")
    # call site in the declared lineage-writer modules must use a registered
    # kind, every registered kind must have a live call site, every declared
    # writer module must actually write — and the INVARIANTS literal must
    # stay in lockstep with the marker-anchored README audit-invariant
    # table, both directions (the JX014 discipline, extended to the audit
    # plane).

    def _provenance_literal(self, m: ModuleFacts, name: str) -> dict[str, int] | None:
        """name -> registry-element line from a module-level tuple-of-tuples
        literal, or None when the literal is missing/empty."""
        for node in m.tree.body:
            if (
                isinstance(node, ast.Assign)
                and len(node.targets) == 1
                and isinstance(node.targets[0], ast.Name)
                and node.targets[0].id == name
                and isinstance(node.value, (ast.Tuple, ast.List))
            ):
                out: dict[str, int] = {}
                for e in node.value.elts:
                    if isinstance(e, (ast.Tuple, ast.List)) and e.elts:
                        s = _const_str(e.elts[0])
                        if s is not None:
                            out.setdefault(s, e.lineno)
                return out or None
        return None

    def _lineage_calls(self, rel: str) -> list[tuple[str | None, int]] | None:
        """(kind-or-None, line) per ``emit_lineage(...)``/``.emit(kind=...)``
        writer call in one module; None when the module is missing or
        unparseable. Matched by NAME (the module-level seam entry point and
        the bound LineageWriter.emit), so a seam cannot dodge the contract
        by aliasing the import."""
        m = self._load(rel)
        if m is None:
            return None
        calls: list[tuple[str | None, int]] = []
        for node in ast.walk(m.tree):
            if not isinstance(node, ast.Call):
                continue
            fn = node.func
            named = (
                isinstance(fn, ast.Name) and fn.id == "emit_lineage"
            ) or (
                isinstance(fn, ast.Attribute) and fn.attr == "emit_lineage"
            )
            if not named:
                continue
            kind = _const_str(node.args[0]) if node.args else None
            calls.append((kind, node.lineno))
        return calls

    def _readme_invariants(self) -> tuple[dict[str, tuple[str, int]], bool]:
        """Invariant names from the marker-anchored README audit-invariant
        table: name -> (doc path, line). Same state machine as the metrics
        table (invariant names are kebab-case, hence the dash in the row
        pattern)."""
        invariants: dict[str, tuple[str, int]] = {}
        saw_marker = False
        for doc in self.config.doc_files:
            lines = self._doc_lines(doc)
            armed = in_table = False
            for i, line in enumerate(lines, start=1):
                if "tpusim-lint: audit-invariant-table" in line:
                    saw_marker = armed = True
                    continue
                is_row = line.lstrip().startswith("|")
                if armed and is_row:
                    armed, in_table = False, True
                if in_table:
                    mrow = re.match(r"\s*\|\s*`([A-Za-z0-9_.-]+)`\s*\|", line)
                    if mrow:
                        invariants.setdefault(mrow.group(1), (doc, i))
                    elif not is_row:
                        in_table = False
        return invariants, saw_marker

    def check_provenance_contract(self) -> Iterator[Finding]:
        rel = self.config.provenance_module
        if not rel:
            return
        m = self._load(rel)
        if m is None:
            yield Finding(
                "JX020", rel, 1, 0,
                "configured provenance-module is missing or unparseable — "
                "the lineage contract has no registry to pin (config drift)",
            )
            return
        kinds = self._provenance_literal(m, "KINDS")
        invariants = self._provenance_literal(m, "INVARIANTS")
        if kinds is None or invariants is None:
            missing = "KINDS" if kinds is None else "INVARIANTS"
            yield m.finding(
                "JX020", m.tree,
                f"no module-level {missing} tuple-of-tuples literal found — "
                f"the provenance universe must be statically readable for "
                f"the seam/README cross-check",
            )
            return
        # Direction 1: every writer call uses a registered kind; every
        # declared writer module actually writes.
        used: dict[str, tuple[str, int]] = {}
        for wrel in self.config.lineage_writer_modules:
            calls = self._lineage_calls(wrel)
            if calls is None:
                yield Finding(
                    "JX020", wrel, 1, 0,
                    "configured lineage-writer module is missing or "
                    "unparseable (config drift)",
                )
                continue
            if not calls:
                yield Finding(
                    "JX020", wrel, 1, 0,
                    "declared lineage-writer module has no emit_lineage(...) "
                    "call site — an artifact-producing seam outside the "
                    "provenance ledger (wire the seam or drop the module "
                    "from lineage-writer-modules)",
                )
                continue
            wm = self._load(wrel)
            for kind, line in calls:
                text = (
                    wm.lines[line - 1].strip()
                    if wm and 0 < line <= len(wm.lines) else ""
                )
                if kind is None:
                    yield Finding(
                        "JX020", wrel, line, 0,
                        "emit_lineage kind must be a string literal — a "
                        "computed kind cannot be cross-checked against the "
                        "KINDS registry",
                        text,
                    )
                elif kind not in kinds:
                    yield Finding(
                        "JX020", wrel, line, 0,
                        f"emit_lineage kind `{kind}` is not in the KINDS "
                        f"registry ({rel}) — register it or fix the typo "
                        f"(the writer raises on it at runtime)",
                        text,
                    )
                else:
                    used.setdefault(kind, (wrel, line))
        # Direction 2: every registered kind has a live seam.
        for kind, line in sorted(kinds.items()):
            if kind not in used:
                text = m.lines[line - 1].strip() if 0 < line <= len(m.lines) else ""
                yield Finding(
                    "JX020", m.path, line, 0,
                    f"registered lineage kind `{kind}` has no "
                    f"emit_lineage call site in the configured writer "
                    f"modules — dead registry entry or unwired seam",
                    text,
                )
        # Direction 3: INVARIANTS <-> README audit-invariant table, both ways.
        documented, saw_marker = self._readme_invariants()
        if not saw_marker:
            if self.config.doc_files:
                doc = self.config.doc_files[0]
                yield self._doc_finding(
                    "JX020", doc, 1,
                    "no `tpusim-lint: audit-invariant-table` marker found in "
                    "the doc files — the audit invariant table cannot be "
                    "cross-checked (add the marker comment above it)",
                    self._doc_lines(doc),
                )
            return
        for inv, line in sorted(invariants.items()):
            if inv not in documented:
                text = m.lines[line - 1].strip() if 0 < line <= len(m.lines) else ""
                yield Finding(
                    "JX020", m.path, line, 0,
                    f"audit invariant `{inv}` is missing from the documented "
                    f"invariant table — an unexplained gate failure nobody "
                    f"can look up",
                    text,
                )
        for inv, (doc, line) in sorted(documented.items()):
            if inv not in invariants:
                yield self._doc_finding(
                    "JX020", doc, line,
                    f"documented audit invariant `{inv}` is verified by no "
                    f"INVARIANTS entry in {rel} — stale table row or renamed "
                    f"invariant",
                    self._doc_lines(doc),
                )


# ---------------------------------------------------------------------------
# Registry + entry point (mirrors rules.ALL_RULES for the project scope).

ContractFn = Callable[[ProjectContracts], Iterator[Finding]]

CONTRACT_RULES: dict[str, tuple[ContractFn, str]] = {
    "JX010": (
        ProjectContracts.check_telemetry,
        "telemetry span/attr consumed but never emitted; schema-v2 row "
        "contract; raw attr subscripts in dashboards",
    ),
    "JX011": (
        ProjectContracts.check_chaos_seams,
        "chaos seam fired/documented/drilled sets disagree",
    ),
    "JX012": (
        ProjectContracts.check_finalize_leaves,
        "finalize leaf outside the combine_sums/strip-list naming contract",
    ),
    "JX013": (
        ProjectContracts.check_cli_flags,
        "README-documented CLI flag no parser declares (docs drift)",
    ),
    "JX014": (
        ProjectContracts.check_metrics_contract,
        "SLO-config metric absent from the metrics registry, or registry/"
        "README metrics-table drift",
    ),
    "JX020": (
        ProjectContracts.check_provenance_contract,
        "lineage kind emitted but unregistered (or registered but never "
        "emitted); writer module without a seam; audit-invariant README "
        "table drift",
    ),
}


def lint_contracts(
    root: Path,
    config: LintConfig | None = None,
    rules=None,
) -> list[Finding]:
    """Run the cross-module contract rules over the project at ``root``.
    ``rules`` filters to a subset of CONTRACT_RULES ids; Python findings
    honor in-file suppression comments, doc/drill findings are baseline-only."""
    config = config or LintConfig()
    enabled = [
        r.upper() for r in (rules if rules is not None else config.enabled_rules)
    ]
    wanted = [r for r in enabled if r in CONTRACT_RULES]
    if not wanted:
        return []
    ctx = ProjectContracts(Path(root), config)
    findings: list[Finding] = []
    # The message is part of the dedup key: one node can carry two DISTINCT
    # JX010 defects (a never-emitted key read through a raw subscript), and
    # collapsing them would silently drop a diagnostic.
    seen: set[tuple[str, str, int, int, str]] = set()
    for rule_id in wanted:
        fn, _ = CONTRACT_RULES[rule_id]
        for f in fn(ctx):
            key = (f.rule, f.path, f.line, f.col, f.message)
            if key in seen:
                continue
            m = ctx.modules.get(f.path)
            if m is not None and m.suppressions.is_suppressed(f.rule, f.line):
                continue
            seen.add(key)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
