"""The rule implementations and the file-walking entry points.

Every rule is a function ``(ModuleAnalysis) -> Iterator[Finding]``; the
registry maps rule ids to (function, one-line description). Precision over
recall: each rule targets the exact failure mode this stack has hit (or
nearly hit) — the suppression syntax and the baseline file absorb the
judgment calls, so a rule firing is worth reading.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Callable, Iterable, Iterator

from .analysis import ModuleAnalysis, assigned_names, dotted_name, structural_taint
from .config import LintConfig
from .findings import Finding

#: jax.random functions that *derive* new key material rather than consuming
#: a key for draws — the sanctioned ways to reuse a name.
_KEY_DERIVERS = frozenset({
    "split", "fold_in", "key", "PRNGKey", "wrap_key_data", "key_data", "clone",
})

#: Host-sync call forms JX002 recognizes.
_SYNC_NP_FUNCS = frozenset({"np.asarray", "np.array", "numpy.asarray", "numpy.array"})
_SYNC_CASTS = frozenset({"int", "float", "bool"})

#: float64-inviting references JX005 flags inside jitted math.
_DTYPE_DRIFT_ATTRS = frozenset({
    "np.float64", "np.int64", "np.double", "np.longdouble",
    "numpy.float64", "numpy.int64", "jnp.float64", "jnp.int64",
})

_JNP_ARRAY_MAKERS = frozenset({"array", "asarray", "full", "full_like"})


def _finding(ma: ModuleAnalysis, rule: str, node: ast.AST, message: str) -> Finding:
    line = getattr(node, "lineno", 1)
    col = getattr(node, "col_offset", 0)
    text = ma.lines[line - 1].strip() if 0 < line <= len(ma.lines) else ""
    return Finding(rule, ma.path, line, col, message, text)


# ---------------------------------------------------------------------------
# JX001 — Python control flow on tracer values in jit-reachable code.


def check_tracer_branch(ma: ModuleAnalysis) -> Iterator[Finding]:
    from .analysis import _expr_tainted

    for info in ma.jit_entered_functions():
        tainted = ma.tracer_tainted_names(info)
        for node in ast.walk(info.node):
            if isinstance(node, (ast.If, ast.While)) and _expr_tainted(
                node.test, tainted
            ):
                kind = "if" if isinstance(node, ast.If) else "while"
                yield _finding(
                    ma, "JX001", node,
                    f"Python `{kind}` on a tracer-typed value inside "
                    f"jit-reachable `{info.qualname}` — this forces a trace-time "
                    f"branch (ConcretizationTypeError or silent retrace per "
                    f"value); use jnp.where/lax.cond",
                )


# ---------------------------------------------------------------------------
# JX002 — implicit host sync in engine/runner hot loops.


def check_host_sync(ma: ModuleAnalysis) -> Iterator[Finding]:
    if not ma.config.matches(ma.path, tuple(ma.config.hot_modules)):
        return
    # Module top level included: a script's main loop is a hot loop too.
    for func in [f.node for f in ma.funcs] + [ma.tree]:
        tainted = ma.device_tainted_names(func)
        for node in ast.walk(func):
            if not isinstance(node, ast.Call):
                continue
            name = dotted_name(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            in_loop = ma.inside_loop(node)
            if leaf == "block_until_ready":
                yield _finding(
                    ma, "JX002", node,
                    "`.block_until_ready()` blocks the dispatch pipeline; "
                    "outside profiling code the transfer at use time is the "
                    "only sync needed",
                )
            elif not in_loop:
                continue
            elif leaf == "item" and not node.args:
                yield _finding(
                    ma, "JX002", node,
                    "`.item()` inside a hot loop synchronously fetches one "
                    "scalar per iteration — batch the transfer outside the loop",
                )
            elif name in _SYNC_NP_FUNCS or name == "jax.device_get":
                yield _finding(
                    ma, "JX002", node,
                    f"`{name}` inside a hot loop forces a device→host "
                    f"transfer per iteration",
                )
            elif (
                name in _SYNC_CASTS
                and len(node.args) == 1
                and structural_taint(node.args[0], tainted)
            ):
                yield _finding(
                    ma, "JX002", node,
                    f"`{name}()` on a device value inside a hot loop blocks "
                    f"until the device catches up — the implicit host sync "
                    f"that serializes pipelined dispatch",
                )


# ---------------------------------------------------------------------------
# JX003 — use-after-donation.


def check_use_after_donation(ma: ModuleAnalysis) -> Iterator[Finding]:
    if not any(jc.donate for jc in ma.jitted.values()):
        return
    # Module top level is a scope too: scripts donate at module scope.
    for func in [f.node for f in ma.funcs] + [ma.tree]:
        # Own scope only (like JX004): a same-named local in a nested closure
        # is a different binding — it must neither mask a real
        # use-after-donation here nor be flagged against this scope's calls.
        stores: dict[str, list[int]] = {}
        for node in ma.own_nodes(func):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                stores.setdefault(node.id, []).append(node.lineno)
        own = list(ma.own_nodes(func))
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            jc = ma.resolve_jitted(node.func)
            if jc is None or not jc.donate:
                continue
            donated = [
                node.args[pos].id
                for pos in jc.donate
                if pos < len(node.args) and isinstance(node.args[pos], ast.Name)
            ]
            if not donated:
                continue
            call_line = node.lineno
            # The call's own extent: its argument reads (which may sit on
            # later physical lines when the call is formatted multi-line)
            # are the donation itself, not a use-after.
            in_call = {id(n) for n in ast.walk(node)}
            call_end = getattr(node, "end_lineno", None) or call_line
            for read in own:
                if (
                    isinstance(read, ast.Name)
                    and isinstance(read.ctx, ast.Load)
                    and id(read) not in in_call
                    and read.id in donated
                    and read.lineno > call_end
                    and not any(
                        call_line <= s <= read.lineno for s in stores.get(read.id, [])
                    )
                    # A read in the opposite arm of an if/else never executes
                    # after this donating call.
                    and not ma.mutually_exclusive(node, read, func)
                ):
                    yield _finding(
                        ma, "JX003", read,
                        f"`{read.id}` was donated to `{jc.key}` (donate_argnums, "
                        f"line {call_line}) and read afterwards — the buffer is "
                        f"deleted on dispatch; reading it raises (or worse, on "
                        f"some backends, returns garbage)",
                    )
            # Donation inside a loop with NO rebind of the name anywhere in
            # the loop body: iteration n+1's reads — including ones lexically
            # BEFORE the call — see the buffer iteration n donated. (The
            # sanctioned pattern rebinds on the call line: `s, ... = f(s, ...)`.)
            loop = ma.enclosing_loop(node)
            if loop is None:
                continue
            for dname in donated:
                if any(
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Store)
                    and n.id == dname
                    for n in ast.walk(loop)
                ):
                    continue
                for read in ast.walk(loop):
                    if (
                        isinstance(read, ast.Name)
                        and isinstance(read.ctx, ast.Load)
                        and read.id == dname
                        and read.lineno < call_line  # later reads: flagged above
                    ):
                        yield _finding(
                            ma, "JX003", read,
                            f"`{read.id}` is donated to `{jc.key}` later in "
                            f"this loop body (line {call_line}) and never "
                            f"rebound in the loop — on the next iteration "
                            f"this read touches the donated buffer",
                        )


# ---------------------------------------------------------------------------
# JX004 — PRNG key consumed twice without split/fold_in.


def check_key_reuse(ma: ModuleAnalysis) -> Iterator[Finding]:
    extra_consumers = set(ma.config.prng_consumers)
    for func in [f.node for f in ma.funcs] + [ma.tree]:
        # Own scope only: a same-named key in a sibling nested function is a
        # different binding, not a reuse of this one.
        stores: dict[str, list[int]] = {}
        for node in ma.own_nodes(func):
            if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
                stores.setdefault(node.id, []).append(node.lineno)
        consumed: dict[str, ast.Call] = {}  # name -> last consumption site
        calls = [n for n in ma.own_nodes(func) if isinstance(n, ast.Call)]
        for node in sorted(calls, key=lambda n: (n.lineno, n.col_offset)):
            name = dotted_name(node.func) or ""
            leaf = name.rsplit(".", 1)[-1]
            consuming = (
                name.startswith("jax.random.") and leaf not in _KEY_DERIVERS
            ) or leaf in extra_consumers
            if not consuming or not node.args:
                continue
            key_arg = node.args[0]
            if not isinstance(key_arg, ast.Name):
                continue
            kname = key_arg.id
            prev = consumed.get(kname)
            if (
                prev is not None
                and not any(
                    prev.lineno <= s <= node.lineno for s in stores.get(kname, [])
                )
                # if/else arms each consume once per execution — not a reuse.
                and not ma.mutually_exclusive(prev, node, func)
            ):
                yield _finding(
                    ma, "JX004", node,
                    f"PRNG state `{kname}` consumed again (previously at line "
                    f"{prev.lineno}) without split/fold_in/advance — identical "
                    f"draws, silently correlated streams",
                )
            consumed[kname] = node
            # Consumption inside a loop with no per-iteration rebind reuses
            # the same state every iteration — unless the key is stored
            # somewhere in the loop body (a split/fold_in rebind) or derives
            # from the loop variable.
            loop = ma.enclosing_loop(node)
            if loop is not None:
                loop_vars = ma.loop_targets(node)
                stored_in_loop = any(
                    isinstance(n, ast.Name)
                    and isinstance(n.ctx, ast.Store)
                    and n.id == kname
                    for n in ast.walk(loop)
                )
                if kname not in loop_vars and not stored_in_loop:
                    yield _finding(
                        ma, "JX004", node,
                        f"PRNG state `{kname}` consumed inside a loop without "
                        f"being advanced per iteration — every iteration draws "
                        f"the same values",
                    )


# ---------------------------------------------------------------------------
# JX005 — dtype drift into jitted math.


def check_dtype_drift(ma: ModuleAnalysis) -> Iterator[Finding]:
    for info in ma.jit_entered_functions():
        for node in ast.walk(info.node):
            if isinstance(node, ast.Attribute):
                name = dotted_name(node)
                if name in _DTYPE_DRIFT_ATTRS:
                    yield _finding(
                        ma, "JX005", node,
                        f"`{name}` inside jit-reachable `{info.qualname}` — "
                        f"64-bit dtypes are emulated (slowly) on TPU and only "
                        f"exist under the compat.enable_x64 shim; keep device "
                        f"math 32-bit",
                    )
            elif isinstance(node, ast.Call):
                cname = dotted_name(node.func) or ""
                for kw in node.keywords:
                    if (
                        kw.arg == "dtype"
                        and isinstance(kw.value, ast.Name)
                        and kw.value.id in ("float", "int")
                    ):
                        yield _finding(
                            ma, "JX005", kw.value,
                            f"builtin `{kw.value.id}` as dtype in jit-reachable "
                            f"`{info.qualname}` resolves to 64-bit under "
                            f"enable_x64 — name an explicit 32-bit dtype",
                        )
                root, _, leaf = cname.partition(".")
                # Only when the float literal is the LAST positional arg: a
                # trailing positional (jnp.asarray(0.5, jnp.float32)) or
                # dtype= keyword pins the dtype explicitly.
                if (
                    root == "jnp"
                    and leaf in _JNP_ARRAY_MAKERS
                    and not any(kw.arg == "dtype" for kw in node.keywords)
                    and node.args
                    and isinstance(node.args[-1], ast.Constant)
                    and isinstance(node.args[-1].value, float)
                ):
                    yield _finding(
                        ma, "JX005", node,
                        f"bare Python float literal materialized by `{cname}` "
                        f"without dtype in jit-reachable `{info.qualname}` — "
                        f"promotes to float64 under enable_x64",
                    )


# ---------------------------------------------------------------------------
# JX006 — recompilation risk: jitted callables fed Python scalars in loops.


def check_recompile_risk(ma: ModuleAnalysis) -> Iterator[Finding]:
    if not ma.jitted:
        return
    for scope in [f.node for f in ma.funcs] + [ma.tree]:
        for node in ma.own_nodes(scope):
            if not isinstance(node, ast.Call):
                continue
            jc = ma.resolve_jitted(node.func)
            if jc is None:
                continue
            if not ma.inside_loop(node, comprehensions=False):
                continue
            loop_vars = ma.loop_targets(node)
            for i, arg in enumerate(node.args):
                if isinstance(arg, ast.Constant) and isinstance(
                    arg.value, (int, float)
                ):
                    yield _finding(
                        ma, "JX006", arg,
                        f"Python scalar literal at position {i} of jitted "
                        f"`{jc.key}` inside a loop — weak-typed scalars hash "
                        f"into the compile cache per value family; pass a "
                        f"committed-dtype array",
                    )
                elif isinstance(arg, ast.Name) and arg.id in loop_vars:
                    yield _finding(
                        ma, "JX006", arg,
                        f"loop variable `{arg.id}` passed raw to jitted "
                        f"`{jc.key}` — a fresh Python int every iteration "
                        f"recompiles (or at best re-hashes) per value; wrap it "
                        f"in jnp.asarray with a pinned dtype",
                    )


# ---------------------------------------------------------------------------
# JX007 — nondeterministic host calls in device-math modules.


def check_nondeterministic_host(ma: ModuleAnalysis) -> Iterator[Finding]:
    if not ma.config.matches(ma.path, tuple(ma.config.device_modules)):
        return
    for node in ast.walk(ma.tree):
        if isinstance(node, ast.Import):
            for alias in node.names:
                if alias.name.split(".")[0] in ("time", "random", "datetime"):
                    yield _finding(
                        ma, "JX007", node,
                        f"`import {alias.name}` in a device-math module — "
                        f"wall-clock/host randomness makes device results "
                        f"unreproducible; keep host I/O in runner/bench",
                    )
        elif isinstance(node, ast.ImportFrom):
            if node.module and node.module.split(".")[0] in (
                "time", "random", "datetime",
            ):
                yield _finding(
                    ma, "JX007", node,
                    f"`from {node.module} import ...` in a device-math module",
                )
        elif isinstance(node, ast.Call):
            name = dotted_name(node.func) or ""
            if name.startswith(("time.", "random.", "datetime.", "np.random.")):
                yield _finding(
                    ma, "JX007", node,
                    f"nondeterministic host call `{name}` in a device-math "
                    f"module — results must be a pure function of (config, seed)",
                )


# ---------------------------------------------------------------------------
# JX008 — unused reachability (dead defs / imports).


def check_unused(ma: ModuleAnalysis) -> Iterator[Finding]:
    if not ma.config.matches(ma.path, tuple(ma.config.unused_globs)):
        return
    loads: set[str] = set()
    strings: set[str] = set()
    for node in ast.walk(ma.tree):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            loads.add(node.id)
        elif isinstance(node, ast.Constant) and isinstance(node.value, str):
            strings.add(node.value)
    for node in ma.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)):
            if (
                node.name not in loads
                and node.name not in strings  # __all__ / getattr by name
                and node.name != "main"
                and not node.decorator_list
            ):
                yield _finding(
                    ma, "JX008", node,
                    f"`{node.name}` is defined but never referenced in this "
                    f"module — dead code accretes in scripts; delete it or "
                    f"note why it must stay",
                )
        elif isinstance(node, (ast.Import, ast.ImportFrom)):
            if isinstance(node, ast.ImportFrom) and node.module == "__future__":
                continue
            for alias in node.names:
                bound = (alias.asname or alias.name).split(".")[0]
                if alias.name == "*":
                    continue
                if bound not in loads and bound not in strings:
                    yield _finding(
                        ma, "JX008", node,
                        f"import `{bound}` is never used in this module",
                    )


# ---------------------------------------------------------------------------
# JX009 — unblocked timing in measurement modules.

#: Clock calls whose assigned-then-subtracted pattern marks a timed interval.
_CLOCK_FUNCS = frozenset({"time.perf_counter", "time.monotonic"})


def check_unblocked_timing(ma: ModuleAnalysis) -> Iterator[Finding]:
    """A ``time.perf_counter()``/``time.monotonic()`` delta that brackets a
    device dispatch with no ``block_until_ready`` between the dispatch and
    the delta: JAX dispatch is asynchronous, so the interval measures launch
    overhead, not execution — the classic timing bug (observed in this repo
    as a 12-chunk program "running" in 46 us; see
    profiling.time_chained_chunks). Dispatches are recognized by the same
    ``device_call_patterns`` the JX002 taint seeds on — the calls whose
    results are unforced device values. Only measurement modules are
    scanned: in orchestration code an unforced interval is often the point
    (pipelined stall accounting times exactly the non-blocking part)."""
    if not ma.config.matches(ma.path, tuple(ma.config.measurement_modules)):
        return
    dispatch_pats = tuple(ma.config.device_call_patterns)
    for func in [f.node for f in ma.funcs] + [ma.tree]:
        own = list(ma.own_nodes(func))
        marks: dict[str, list[int]] = {}
        for node in own:
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                if (dotted_name(node.value.func) or "") in _CLOCK_FUNCS:
                    for tgt in node.targets:
                        if isinstance(tgt, ast.Name):
                            marks.setdefault(tgt.id, []).append(node.lineno)
        if not marks:
            continue
        dispatches: list[tuple[int, str]] = []
        syncs: list[int] = []
        for node in own:
            if not isinstance(node, ast.Call):
                continue
            if isinstance(node.func, ast.Attribute):
                # The attr directly, not via dotted_name: a sync often hangs
                # off a call result (`fin().block_until_ready()`), whose
                # base dotted_name cannot resolve.
                leaf = node.func.attr
            else:
                leaf = (dotted_name(node.func) or "").rsplit(".", 1)[-1]
            if leaf == "block_until_ready":
                syncs.append(node.lineno)
            elif any(p in leaf for p in dispatch_pats):
                dispatches.append((node.lineno, leaf))
        if not dispatches:
            continue
        for node in own:
            if not (isinstance(node, ast.BinOp) and isinstance(node.op, ast.Sub)):
                continue
            right = node.right
            if not (isinstance(right, ast.Name) and right.id in marks):
                continue
            if isinstance(node.left, ast.Call):
                left_is_clock = (dotted_name(node.left.func) or "") in _CLOCK_FUNCS
            elif isinstance(node.left, ast.Name):
                left_is_clock = node.left.id in marks
            else:
                left_is_clock = False
            if not left_is_clock:
                continue
            starts = [ln for ln in marks[right.id] if ln <= node.lineno]
            if not starts:
                continue
            t0_line = max(starts)  # the closest preceding re-mark wins
            bracketed = [
                (ln, leaf) for ln, leaf in dispatches if t0_line <= ln <= node.lineno
            ]
            if not bracketed:
                continue
            last_dispatch = max(ln for ln, _ in bracketed)
            if any(last_dispatch <= s <= node.lineno for s in syncs):
                continue
            leaves = sorted({leaf for _, leaf in bracketed})
            yield _finding(
                ma, "JX009", node,
                f"timed interval (lines {t0_line}-{node.lineno}) brackets "
                f"device dispatch `{', '.join(leaves)}` with no "
                f"block_until_ready before the delta — async dispatch "
                f"returns immediately, so this measures launch overhead, "
                f"not execution",
            )


# ---------------------------------------------------------------------------
# Registry + entry points.

RuleFn = Callable[[ModuleAnalysis], Iterator[Finding]]

ALL_RULES: dict[str, tuple[RuleFn, str]] = {
    "JX001": (check_tracer_branch, "Python if/while on tracer values in jit-reachable code"),
    "JX002": (check_host_sync, "implicit host sync in engine/runner hot loops"),
    "JX003": (check_use_after_donation, "read of a buffer after donate_argnums donation"),
    "JX004": (check_key_reuse, "PRNG state consumed twice without split/advance"),
    "JX005": (check_dtype_drift, "64-bit dtype drift into jitted math (x64 shim)"),
    "JX006": (check_recompile_risk, "jitted callable fed Python scalars inside loops"),
    "JX007": (check_nondeterministic_host, "time/random host calls in device-math modules"),
    "JX008": (check_unused, "unused module-level defs/imports (scripts)"),
    "JX009": (check_unblocked_timing, "clock delta around a device dispatch with no block_until_ready"),
}


def lint_source(
    source: str,
    path: str,
    config: LintConfig | None = None,
    rules: Iterable[str] | None = None,
) -> list[Finding]:
    """Lint one source text as repo-relative ``path``; suppression comments
    honored, baseline not applied (that is the CLI's job)."""
    config = config or LintConfig()
    enabled = tuple(rules) if rules is not None else config.enabled_rules
    try:
        ma = ModuleAnalysis(path, source, config)
    except SyntaxError as e:
        return [
            Finding("JX000", path, e.lineno or 1, 0, f"syntax error: {e.msg}")
        ]
    findings: list[Finding] = []
    seen: set[tuple[str, int, int, str]] = set()
    for rule_id in enabled:
        entry = ALL_RULES.get(rule_id.upper())
        if entry is None:
            continue
        for f in entry[0](ma):
            # (rule, line, col) — the same offending node reached through
            # several enclosing scopes (outer closure + nested def) is ONE
            # finding.
            key = (f.rule, f.line, f.col)
            if key in seen or ma.suppressions.is_suppressed(f.rule, f.line):
                continue
            seen.add(key)
            findings.append(f)
    findings.sort(key=lambda f: (f.line, f.col, f.rule))
    return findings


def lint_paths(
    paths: Iterable[Path],
    root: Path,
    config: LintConfig | None = None,
    rules: Iterable[str] | None = None,
) -> list[Finding]:
    config = config or LintConfig()
    findings: list[Finding] = []
    for p in paths:
        try:
            rel = p.resolve().relative_to(root.resolve()).as_posix()
        except ValueError:  # outside the repo root: keep the path verbatim
            rel = p.as_posix()
        findings.extend(
            lint_source(p.read_text(), rel, config=config, rules=rules)
        )
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
