"""Lint configuration: the ``[tool.tpusim-lint]`` block of pyproject.toml.

Defaults are this repository's real layout — the linter is project-aware by
construction, and the config block exists so the knowledge lives in ONE
committed place instead of being hardcoded across rules:

  * ``include``/``exclude`` — which files a bare ``tpusim lint`` walks;
  * ``hot_modules`` — dispatch hot paths where an implicit host sync (JX002)
    stalls the device pipeline;
  * ``device_modules`` — pure device-math modules where any ``time``/
    ``random`` host call (JX007) is a determinism bug;
  * ``unused_globs`` — where the unused-reachability pass (JX008) applies
    (scripts only: package modules export public API the pass cannot see);
  * ``device_call_patterns`` — method-name substrings whose call results are
    device values for the JX002 taint (the engine's jitted entry points);
  * ``prng_consumers`` — extra PRNG-consuming callables for JX004 beyond
    ``jax.random.*`` (the xoroshiro sequential generator);
  * ``measurement_modules`` — benchmark/profiling code where an unblocked
    clock delta around a device dispatch (JX009) measures launch overhead
    instead of execution.

TOML parsing uses the stdlib ``tomllib`` when present (3.11+) and falls back
to ``tomli`` on 3.10; with neither available the committed defaults below
apply unchanged (they ARE this repo's pyproject block), so the gate still
runs — it just cannot pick up local config edits.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from pathlib import Path

try:  # Python 3.11+
    import tomllib as _toml
except ImportError:  # 3.10: the container ships tomli
    try:
        import tomli as _toml  # type: ignore[no-redef]
    except ImportError:  # pragma: no cover - neither present
        _toml = None

# NOTE: pathlib's ``**`` does not cover the zero-directory case on 3.10, so
# the package root needs its own glob next to the recursive one.
_DEFAULT_INCLUDE = ("tpusim/*.py", "tpusim/**/*.py", "scripts/*.py", "bench.py")
_DEFAULT_EXCLUDE = ("tpusim/lint/*.py",)
_DEFAULT_HOT = (
    "tpusim/engine.py",
    "tpusim/pallas_engine.py",
    "tpusim/runner.py",
    "bench.py",
)
_DEFAULT_DEVICE = (
    "tpusim/state.py",
    "tpusim/sampling.py",
    "tpusim/xoroshiro.py",
    "tpusim/engine.py",
    "tpusim/pallas_engine.py",
)
_DEFAULT_UNUSED = ("scripts/*.py",)
_DEFAULT_DEVICE_CALLS = (
    "_pipe_chunk",
    "_chunk",
    "_init",
    "_finalize",
    "_run_device",
    "run_batch_async",
)
_DEFAULT_PRNG_CONSUMERS = ("next_words",)
_DEFAULT_MEASUREMENT = (
    "bench.py",
    "tpusim/profiling.py",
    "tpusim/perf.py",
    "scripts/*.py",
)
# -- Contract-pass knowledge (tpusim.lint.contracts, JX010-JX013). ----------
#: The telemetry protocol's producer AND consumer modules: emit sites, the
#: dashboards' attrs/span reads, and the attr-returning helpers the
#: ``**spread`` resolver follows (engine/pallas memory_attrs live here too).
_DEFAULT_TELEMETRY_MODULES = (
    "tpusim/telemetry.py",
    "tpusim/runner.py",
    "tpusim/sweep.py",
    "tpusim/packed.py",
    "tpusim/fleet.py",
    "tpusim/chaos.py",
    "tpusim/flight_export.py",
    "tpusim/report.py",
    "tpusim/watch.py",
    "tpusim/tracing.py",
    "tpusim/convergence.py",
    "tpusim/engine.py",
    "tpusim/pallas_engine.py",
)
#: Where the span row literal lives (the schema-v2 source of truth).
_DEFAULT_SPAN_WRITER = "tpusim/telemetry.py:TelemetryRecorder.emit"
#: Row fields every v2 span line must carry (parent_span is conditional).
_DEFAULT_SCHEMA_REQUIRED = (
    "run_id", "span", "t_start", "t_mono", "dur_s", "schema", "process",
    "trace_id", "attrs",
)
#: Methods whose keyword names flow into later spans (CompileLedger context).
_DEFAULT_CONTEXT_METHODS = ("set_context",)
#: Committed chaos drill plans (JX011's drilled-seam source).
_DEFAULT_DRILL_GLOBS = ("drills/*.json",)
#: Docs the contract pass cross-checks: the chaos seam table and span-schema
#: markers, and the JX013 flag scan.
_DEFAULT_DOC_FILES = ("README.md", "drills/README.md")
#: Engine modules whose output-dict stores define the finalize leaf set.
_DEFAULT_ENGINE_LEAF_MODULES = ("tpusim/engine.py", "tpusim/pallas_engine.py")
#: Dict names the engines build run_batch outputs in.
_DEFAULT_LEAF_DICT_NAMES = ("sums", "out", "dev_sums", "loop_out_specs")
#: Orchestration modules that read finalize leaves by name (runner first:
#: it is also where the strip-prefix literals are verified).
_DEFAULT_LEAF_CONSUMERS = ("tpusim/runner.py", "tpusim/packed.py")
#: Telemetry leaf prefixes the runner strips from the stat/checkpoint path.
_DEFAULT_LEAF_STRIP_PREFIXES = ("tele_", "stats_", "flight_")
#: Merge-describing suffixes combine_sums keys on (additive/max/concat).
_DEFAULT_LEAF_MERGE_SUFFIXES = ("_sum", "_max", "_per_run")
#: The prefix/suffix literals combine_sums must TEST (its non-additive merge
#: branches); "_sum" is the additive default and needs no test.
_DEFAULT_COMBINE_MERGE_LITERALS = ("flight_", "_per_run", "_max")
#: Scalar leaves exempt from the naming contract (additive by construction).
_DEFAULT_LEAF_SCALARS = ("runs", "n_chunks", "unfinished")
#: Modules whose argparse add_argument calls declare the CLI flag universe.
_DEFAULT_CLI_MODULES = (
    "tpusim/cli.py",
    "tpusim/lint/cli.py",
    "tpusim/report.py",
    "tpusim/watch.py",
    "tpusim/sweep.py",
    "tpusim/fleet.py",
    "tpusim/perf.py",
    "tpusim/flight_export.py",
    "tpusim/tracing.py",
    "tpusim/analysis/plots.py",
    "bench.py",
    "scripts/*.py",
)
#: Documented flags that belong to external tools, not this CLI.
_DEFAULT_FLAG_IGNORE = ()
#: Dict receivers in the leaf-consumer modules whose string-keyed reads ARE
#: engine finalize-leaf consumption (the JX012 cross-check set); generic
#: summary/config dicts that merely reuse a leaf-ish suffix stay out.
_DEFAULT_LEAF_READ_NAMES = ("raw", "tele_b", "batch_sums")
#: Modules that consume packed per-run leaves (``*_per_run`` / ``flight_*``)
#: at piece boundaries — JX012's packed sub-check requires every such leaf an
#: engine stores to be read by constant name in one of these, or listed in
#: ``packed-leaf-strip``.
_DEFAULT_PACKED_CONSUMERS = ("tpusim/packed.py", "tpusim/flight_export.py")
#: Packed per-run leaves explicitly declared as dropped at piece boundaries
#: (escape hatch for leaves that are intentionally not sliced per point).
_DEFAULT_PACKED_LEAF_STRIP: tuple[str, ...] = ()
#: Where the metrics registry literal (``METRICS`` tuple-of-tuples) lives —
#: JX014's source of truth for the exported metric-family universe.
_DEFAULT_METRICS_MODULE = "tpusim/metrics.py"
#: Configs whose SLO objectives (``[tool.tpusim-slo]`` / JSON "objectives")
#: may only reference registered metric families (JX014).
_DEFAULT_SLO_CONFIG_FILES = ("pyproject.toml",)
#: Where the provenance registries (``KINDS``/``INVARIANTS`` tuples) live —
#: JX020's source of truth for the lineage-record universe.
_DEFAULT_PROVENANCE_MODULE = "tpusim/provenance.py"
#: Modules with artifact-producing seams: each must hold at least one
#: ``emit_lineage(...)`` call, every call's kind must be registered, and
#: every registered kind must have a call site (JX020).
_DEFAULT_LINEAGE_WRITER_MODULES = (
    "tpusim/runner.py",
    "tpusim/sweep.py",
    "tpusim/packed.py",
    "tpusim/fleet.py",
    "tpusim/perf.py",
    "tpusim/flight_export.py",
)
# -- Concurrency-pass knowledge (tpusim.lint.concurrency, JX015-JX019). -----
#: Modules that create threads, hold locks, or run in thread context today
#: (fleet heartbeat, chaos watchdog, metrics HTTP server, bench hard
#: watchdog) plus engine.py so the pipelined done-flag path is covered —
#: the future `tpusim serve` modules join this list the day they appear.
_DEFAULT_THREAD_MODULES = (
    "tpusim/chaos.py",
    "tpusim/engine.py",
    "tpusim/fleet.py",
    "tpusim/metrics.py",
    "bench.py",
)
#: Attribute/variable leaf names that ARE locks for the with-lock dataflow
#: (names assigned from ``threading.Lock()`` are recognized regardless).
_DEFAULT_LOCK_ATTRS = ("_lock", "lock", "_mutex")
#: Call patterns that block (JX018) when made inside a held-lock region;
#: dotted entries match the full dotted call, bare entries match the leaf
#: (timed ``.wait(t)``/``.get(timeout=)`` variants are exempt).
_DEFAULT_BLOCKING_CALLS = (
    "subprocess.run",
    "subprocess.call",
    "subprocess.check_call",
    "subprocess.check_output",
    "wait",
    "communicate",
    "accept",
    "serve_forever",
    "sleep",
)
_ALL_RULE_IDS = tuple(f"JX{n:03d}" for n in range(1, 21))


@dataclasses.dataclass(frozen=True)
class LintConfig:
    include: tuple[str, ...] = _DEFAULT_INCLUDE
    exclude: tuple[str, ...] = _DEFAULT_EXCLUDE
    enabled_rules: tuple[str, ...] = _ALL_RULE_IDS
    hot_modules: tuple[str, ...] = _DEFAULT_HOT
    device_modules: tuple[str, ...] = _DEFAULT_DEVICE
    unused_globs: tuple[str, ...] = _DEFAULT_UNUSED
    device_call_patterns: tuple[str, ...] = _DEFAULT_DEVICE_CALLS
    prng_consumers: tuple[str, ...] = _DEFAULT_PRNG_CONSUMERS
    measurement_modules: tuple[str, ...] = _DEFAULT_MEASUREMENT
    # Contract-pass knowledge (JX010-JX013; tpusim.lint.contracts).
    telemetry_modules: tuple[str, ...] = _DEFAULT_TELEMETRY_MODULES
    span_writer: str = _DEFAULT_SPAN_WRITER
    span_schema_required: tuple[str, ...] = _DEFAULT_SCHEMA_REQUIRED
    context_methods: tuple[str, ...] = _DEFAULT_CONTEXT_METHODS
    drill_globs: tuple[str, ...] = _DEFAULT_DRILL_GLOBS
    doc_files: tuple[str, ...] = _DEFAULT_DOC_FILES
    engine_leaf_modules: tuple[str, ...] = _DEFAULT_ENGINE_LEAF_MODULES
    leaf_dict_names: tuple[str, ...] = _DEFAULT_LEAF_DICT_NAMES
    leaf_consumer_modules: tuple[str, ...] = _DEFAULT_LEAF_CONSUMERS
    leaf_read_names: tuple[str, ...] = _DEFAULT_LEAF_READ_NAMES
    leaf_strip_prefixes: tuple[str, ...] = _DEFAULT_LEAF_STRIP_PREFIXES
    leaf_merge_suffixes: tuple[str, ...] = _DEFAULT_LEAF_MERGE_SUFFIXES
    combine_merge_literals: tuple[str, ...] = _DEFAULT_COMBINE_MERGE_LITERALS
    leaf_scalar_allowlist: tuple[str, ...] = _DEFAULT_LEAF_SCALARS
    packed_consumer_modules: tuple[str, ...] = _DEFAULT_PACKED_CONSUMERS
    packed_leaf_strip: tuple[str, ...] = _DEFAULT_PACKED_LEAF_STRIP
    cli_modules: tuple[str, ...] = _DEFAULT_CLI_MODULES
    flag_ignore: tuple[str, ...] = _DEFAULT_FLAG_IGNORE
    metrics_module: str = _DEFAULT_METRICS_MODULE
    slo_config_files: tuple[str, ...] = _DEFAULT_SLO_CONFIG_FILES
    provenance_module: str = _DEFAULT_PROVENANCE_MODULE
    lineage_writer_modules: tuple[str, ...] = _DEFAULT_LINEAGE_WRITER_MODULES
    # Concurrency-pass knowledge (JX015-JX019; tpusim.lint.concurrency).
    thread_modules: tuple[str, ...] = _DEFAULT_THREAD_MODULES
    lock_attr_names: tuple[str, ...] = _DEFAULT_LOCK_ATTRS
    blocking_call_patterns: tuple[str, ...] = _DEFAULT_BLOCKING_CALLS

    def matches(self, rel_path: str, globs: tuple[str, ...]) -> bool:
        rel = rel_path.replace("\\", "/")
        return any(fnmatch.fnmatch(rel, g) for g in globs)

    def is_included(self, rel_path: str) -> bool:
        return self.matches(rel_path, self.include) and not self.matches(
            rel_path, self.exclude
        )


def load_config(pyproject: Path | None = None) -> LintConfig:
    """Read ``[tool.tpusim-lint]`` from ``pyproject`` (or the repo root's).
    Missing file, missing block, or no TOML parser all yield the defaults —
    the linter must run in a bare checkout."""
    if pyproject is None:
        pyproject = Path(__file__).resolve().parents[2] / "pyproject.toml"
    if _toml is None or not pyproject.exists():
        return LintConfig()
    with pyproject.open("rb") as fh:
        data = _toml.load(fh)
    block = data.get("tool", {}).get("tpusim-lint", {})
    kwargs = {}
    for field, key in (
        ("include", "include"),
        ("exclude", "exclude"),
        ("enabled_rules", "enabled-rules"),
        ("hot_modules", "hot-modules"),
        ("device_modules", "device-modules"),
        ("unused_globs", "unused-globs"),
        ("device_call_patterns", "device-call-patterns"),
        ("prng_consumers", "prng-consumers"),
        ("measurement_modules", "measurement-modules"),
        ("telemetry_modules", "telemetry-modules"),
        ("span_schema_required", "span-schema-required"),
        ("context_methods", "context-methods"),
        ("drill_globs", "drill-globs"),
        ("doc_files", "doc-files"),
        ("engine_leaf_modules", "engine-leaf-modules"),
        ("leaf_dict_names", "leaf-dict-names"),
        ("leaf_consumer_modules", "leaf-consumer-modules"),
        ("leaf_read_names", "leaf-read-names"),
        ("leaf_strip_prefixes", "leaf-strip-prefixes"),
        ("leaf_merge_suffixes", "leaf-merge-suffixes"),
        ("combine_merge_literals", "combine-merge-literals"),
        ("leaf_scalar_allowlist", "leaf-scalar-allowlist"),
        ("packed_consumer_modules", "packed-consumer-modules"),
        ("packed_leaf_strip", "packed-leaf-strip"),
        ("cli_modules", "cli-modules"),
        ("flag_ignore", "flag-ignore"),
        ("slo_config_files", "slo-config-files"),
        ("lineage_writer_modules", "lineage-writer-modules"),
        ("thread_modules", "thread-modules"),
        ("lock_attr_names", "lock-attr-names"),
        ("blocking_call_patterns", "blocking-call-patterns"),
    ):
        if key in block:
            kwargs[field] = tuple(str(v) for v in block[key])
    if "span-writer" in block:
        kwargs["span_writer"] = str(block["span-writer"])
    if "metrics-module" in block:
        kwargs["metrics_module"] = str(block["metrics-module"])
    if "provenance-module" in block:
        kwargs["provenance_module"] = str(block["provenance-module"])
    return LintConfig(**kwargs)
