"""Lint configuration: the ``[tool.tpusim-lint]`` block of pyproject.toml.

Defaults are this repository's real layout — the linter is project-aware by
construction, and the config block exists so the knowledge lives in ONE
committed place instead of being hardcoded across rules:

  * ``include``/``exclude`` — which files a bare ``tpusim lint`` walks;
  * ``hot_modules`` — dispatch hot paths where an implicit host sync (JX002)
    stalls the device pipeline;
  * ``device_modules`` — pure device-math modules where any ``time``/
    ``random`` host call (JX007) is a determinism bug;
  * ``unused_globs`` — where the unused-reachability pass (JX008) applies
    (scripts only: package modules export public API the pass cannot see);
  * ``device_call_patterns`` — method-name substrings whose call results are
    device values for the JX002 taint (the engine's jitted entry points);
  * ``prng_consumers`` — extra PRNG-consuming callables for JX004 beyond
    ``jax.random.*`` (the xoroshiro sequential generator);
  * ``measurement_modules`` — benchmark/profiling code where an unblocked
    clock delta around a device dispatch (JX009) measures launch overhead
    instead of execution.

TOML parsing uses the stdlib ``tomllib`` when present (3.11+) and falls back
to ``tomli`` on 3.10; with neither available the committed defaults below
apply unchanged (they ARE this repo's pyproject block), so the gate still
runs — it just cannot pick up local config edits.
"""

from __future__ import annotations

import dataclasses
import fnmatch
from pathlib import Path

try:  # Python 3.11+
    import tomllib as _toml
except ImportError:  # 3.10: the container ships tomli
    try:
        import tomli as _toml  # type: ignore[no-redef]
    except ImportError:  # pragma: no cover - neither present
        _toml = None

# NOTE: pathlib's ``**`` does not cover the zero-directory case on 3.10, so
# the package root needs its own glob next to the recursive one.
_DEFAULT_INCLUDE = ("tpusim/*.py", "tpusim/**/*.py", "scripts/*.py", "bench.py")
_DEFAULT_EXCLUDE = ("tpusim/lint/*.py",)
_DEFAULT_HOT = (
    "tpusim/engine.py",
    "tpusim/pallas_engine.py",
    "tpusim/runner.py",
    "bench.py",
)
_DEFAULT_DEVICE = (
    "tpusim/state.py",
    "tpusim/sampling.py",
    "tpusim/xoroshiro.py",
    "tpusim/engine.py",
    "tpusim/pallas_engine.py",
)
_DEFAULT_UNUSED = ("scripts/*.py",)
_DEFAULT_DEVICE_CALLS = (
    "_pipe_chunk",
    "_chunk",
    "_init",
    "_finalize",
    "_run_device",
    "run_batch_async",
)
_DEFAULT_PRNG_CONSUMERS = ("next_words",)
_DEFAULT_MEASUREMENT = (
    "bench.py",
    "tpusim/profiling.py",
    "tpusim/perf.py",
    "scripts/*.py",
)
_ALL_RULE_IDS = tuple(f"JX{n:03d}" for n in range(1, 10))


@dataclasses.dataclass(frozen=True)
class LintConfig:
    include: tuple[str, ...] = _DEFAULT_INCLUDE
    exclude: tuple[str, ...] = _DEFAULT_EXCLUDE
    enabled_rules: tuple[str, ...] = _ALL_RULE_IDS
    hot_modules: tuple[str, ...] = _DEFAULT_HOT
    device_modules: tuple[str, ...] = _DEFAULT_DEVICE
    unused_globs: tuple[str, ...] = _DEFAULT_UNUSED
    device_call_patterns: tuple[str, ...] = _DEFAULT_DEVICE_CALLS
    prng_consumers: tuple[str, ...] = _DEFAULT_PRNG_CONSUMERS
    measurement_modules: tuple[str, ...] = _DEFAULT_MEASUREMENT

    def matches(self, rel_path: str, globs: tuple[str, ...]) -> bool:
        rel = rel_path.replace("\\", "/")
        return any(fnmatch.fnmatch(rel, g) for g in globs)

    def is_included(self, rel_path: str) -> bool:
        return self.matches(rel_path, self.include) and not self.matches(
            rel_path, self.exclude
        )


def load_config(pyproject: Path | None = None) -> LintConfig:
    """Read ``[tool.tpusim-lint]`` from ``pyproject`` (or the repo root's).
    Missing file, missing block, or no TOML parser all yield the defaults —
    the linter must run in a bare checkout."""
    if pyproject is None:
        pyproject = Path(__file__).resolve().parents[2] / "pyproject.toml"
    if _toml is None or not pyproject.exists():
        return LintConfig()
    with pyproject.open("rb") as fh:
        data = _toml.load(fh)
    block = data.get("tool", {}).get("tpusim-lint", {})
    kwargs = {}
    for field, key in (
        ("include", "include"),
        ("exclude", "exclude"),
        ("enabled_rules", "enabled-rules"),
        ("hot_modules", "hot-modules"),
        ("device_modules", "device-modules"),
        ("unused_globs", "unused-globs"),
        ("device_call_patterns", "device-call-patterns"),
        ("prng_consumers", "prng-consumers"),
        ("measurement_modules", "measurement-modules"),
    ):
        if key in block:
            kwargs[field] = tuple(str(v) for v in block[key])
    return LintConfig(**kwargs)
