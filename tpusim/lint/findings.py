"""Finding record + the suppression-comment scanner.

A finding's *fingerprint* is deliberately line-number-free: ``(rule, path,
normalized source line, occurrence index)``. Baselined findings must survive
unrelated edits above them — a fingerprint keyed on line numbers would
invalidate the whole baseline on every insertion, and one keyed on the raw
line would churn on re-indents.
"""

from __future__ import annotations

import dataclasses
import re

#: ``# tpusim-lint: disable=JX001,JX003 -- optional reason``
_SUPPRESS_RE = re.compile(
    r"#\s*tpusim-lint:\s*disable=(?P<rules>[A-Za-z0-9,\s]+?)"
    r"(?:\s*--\s*(?P<reason>.*))?\s*$"
)


@dataclasses.dataclass(frozen=True)
class Finding:
    rule: str  # "JX001" .. "JX008"
    path: str  # repo-relative posix path
    line: int  # 1-based
    col: int  # 0-based
    message: str
    source_line: str = ""  # stripped text of the offending line

    def fingerprint(self, occurrence: int) -> str:
        norm = " ".join(self.source_line.split())
        return f"{self.rule}|{self.path}|{norm}|{occurrence}"

    def render(self) -> str:
        return f"{self.path}:{self.line}:{self.col + 1}: {self.rule} {self.message}"


def fingerprint_findings(findings: list[Finding]) -> list[tuple[Finding, str]]:
    """Pair every finding with its occurrence-indexed fingerprint: the i-th
    finding of the same (rule, path, normalized line) gets occurrence i, so
    two identical offending lines in one file baseline independently."""
    seen: dict[str, int] = {}
    out = []
    for f in sorted(findings, key=lambda f: (f.path, f.line, f.col, f.rule)):
        key = f.fingerprint(0).rsplit("|", 1)[0]
        occ = seen.get(key, 0)
        seen[key] = occ + 1
        out.append((f, f"{key}|{occ}"))
    return out


class Suppressions:
    """Per-line suppression sets parsed from the raw source.

    A trailing comment suppresses its own line. A comment that is the only
    content of its line suppresses the *next* line — the idiom for statements
    too long to annotate in place. ``disable=all`` suppresses every rule.
    """

    def __init__(self, source: str):
        self._by_line: dict[int, set[str]] = {}
        lines = source.splitlines()
        for lineno, text in enumerate(lines, start=1):
            m = _SUPPRESS_RE.search(text)
            if not m:
                continue
            rules = {
                r.strip().upper() for r in m.group("rules").split(",") if r.strip()
            }
            target = lineno
            if text.lstrip().startswith("#"):
                # Comment-only line: the suppression covers the next CODE
                # line — reason strings may wrap over several comment lines.
                target = lineno + 1
                while target <= len(lines) and (
                    not lines[target - 1].strip()
                    or lines[target - 1].lstrip().startswith("#")
                ):
                    target += 1
            self._by_line.setdefault(target, set()).update(rules)

    def extend_spans(self, tree) -> None:
        """Widen each suppression to the full extent of any statement that
        STARTS on its target line: findings anchor on the AST node's line,
        which for a black-formatted multi-line statement can be a
        continuation line of the statement the comment covers."""
        import ast

        for node in ast.walk(tree):
            if not isinstance(node, ast.stmt):
                continue
            start = getattr(node, "lineno", None)
            end = getattr(node, "end_lineno", None)
            if start is None or end is None or end <= start:
                continue
            rules = self._by_line.get(start)
            if rules:
                for line in range(start + 1, end + 1):
                    self._by_line.setdefault(line, set()).update(rules)

    def is_suppressed(self, rule: str, line: int) -> bool:
        rules = self._by_line.get(line)
        return bool(rules) and (rule.upper() in rules or "ALL" in rules)
