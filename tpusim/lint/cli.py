"""``tpusim lint`` CLI: walk the configured file set, apply the rules, and
gate on the baseline.

Exit codes: 0 = no non-baselined findings, 1 = new findings (the CI gate),
2 = usage error. ``--write-baseline`` regenerates the committed baseline
from the current findings and exits 0 — the workflow for grandfathering.

    python -m tpusim.cli lint --baseline .tpusim-lint-baseline.json
    python -m tpusim.cli lint tpusim/engine.py --rules JX002,JX003
    python -m tpusim.cli lint --list-rules
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

from .baseline import Baseline
from .concurrency import CONCURRENCY_RULES, lint_concurrency
from .config import load_config
from .contracts import CONTRACT_RULES, lint_contracts
from .rules import ALL_RULES, lint_paths


def _repo_root() -> Path:
    """The project being linted: nearest ancestor of the CWD with a
    pyproject.toml (so an installed tpusim lints the checkout it is run *in*,
    not its own site-packages), falling back to this package's checkout."""
    cur = Path.cwd().resolve()
    for cand in (cur, *cur.parents):
        if (cand / "pyproject.toml").exists():
            return cand
    return Path(__file__).resolve().parents[2]


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(prog="tpusim lint", description=__doc__)
    p.add_argument(
        "paths", nargs="*", type=Path,
        help="files or directories to lint (default: the include globs of "
        "[tool.tpusim-lint] in pyproject.toml)",
    )
    p.add_argument(
        "--baseline", type=Path, metavar="FILE",
        help="subtract grandfathered findings recorded in FILE; exit 1 only "
        "on new ones",
    )
    p.add_argument(
        "--write-baseline", type=Path, metavar="FILE",
        help="rewrite FILE from the current findings and exit 0",
    )
    p.add_argument(
        "--rules", type=str, default=None,
        help="comma-separated rule ids to run (default: enabled-rules config)",
    )
    p.add_argument(
        "--format", choices=("text", "json", "github"), default="text",
        help="finding output format (github = workflow-annotation lines)",
    )
    p.add_argument("--list-rules", action="store_true", help="print the rule table")
    p.add_argument("--quiet", action="store_true", help="suppress the summary line")
    return p


def _collect_files(args, root: Path, config) -> list[Path]:
    if args.paths:
        # Directories expand under the include/exclude config (so
        # `lint tpusim` and the bare CI invocation agree on the file set);
        # an explicitly named FILE is linted unconditionally — the user
        # asked for it by name. Deduplicated: a repeated path must not
        # double findings (and shift baseline occurrence indices).
        files: list[Path] = []
        seen: set[Path] = set()

        def add(f: Path) -> None:
            f = f.resolve()
            if f not in seen:
                seen.add(f)
                files.append(f)

        for p in args.paths:
            p = p if p.is_absolute() else Path.cwd() / p
            if p.is_dir():
                for f in sorted(p.rglob("*.py")):
                    try:
                        rel = f.resolve().relative_to(root.resolve()).as_posix()
                    except ValueError:
                        add(f)  # outside the project: no config opinion
                        continue
                    if config.is_included(rel):
                        add(f)
            elif p.exists():
                add(p)
            else:
                raise SystemExit(f"error: no such path: {p}")
        return files
    files = []
    for pattern in config.include:
        files.extend(root.glob(pattern))
    out = []
    for f in sorted(set(files)):
        rel = f.relative_to(root).as_posix()
        if config.is_included(rel):
            out.append(f)
    return out


def main(argv: list[str] | None = None) -> int:
    args = build_parser().parse_args(argv)
    root = _repo_root()
    config = load_config(root / "pyproject.toml")
    if args.list_rules:
        # Annotated with the PROJECT's enabled-rules state: the CI
        # rule-count floor greps this output, and a pyproject enabled-rules
        # regression must show up here as "(disabled)" — a registry-only
        # listing would stay green while the gate silently stopped running
        # the rule.
        table = {
            **{rid: desc for rid, (_, desc) in ALL_RULES.items()},
            **{rid: desc for rid, (_, desc) in CONTRACT_RULES.items()},
            **{rid: desc for rid, (_, desc) in CONCURRENCY_RULES.items()},
        }
        enabled = {r.upper() for r in config.enabled_rules}
        for rule_id in sorted(table):
            mark = "" if rule_id in enabled else "  (disabled)"
            print(f"{rule_id}{mark}  {table[rule_id]}")
        return 0
    rules = None
    if args.rules:
        rules = [r.strip().upper() for r in args.rules.split(",") if r.strip()]
        unknown = [
            r for r in rules
            if r not in ALL_RULES
            and r not in CONTRACT_RULES
            and r not in CONCURRENCY_RULES
        ]
        if unknown:
            print(f"error: unknown rule(s): {', '.join(unknown)}", file=sys.stderr)
            return 2
    files = _collect_files(args, root, config)
    findings = lint_paths(files, root, config=config, rules=rules)
    # The cross-module contract pass (JX010-JX013) reads its own configured
    # module/doc/drill set from the project root — a partial file list cannot
    # see a cross-module contract, so it runs on the full walk (no explicit
    # paths) or when a contract rule is requested by id.
    # Upper-cased like lint_source's rule matching: lowercase ids in a
    # pyproject enabled-rules list must not silently disable the contract
    # pass while --list-rules reports it enabled.
    enabled = [
        r.upper() for r in (rules if rules is not None else config.enabled_rules)
    ]
    wants_contracts = any(r in CONTRACT_RULES for r in enabled)
    if wants_contracts and (not args.paths or rules is not None):
        findings.extend(lint_contracts(root, config=config, rules=enabled))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    # The thread-safety pass (JX015-JX019) is whole-project for the same
    # reason: lock-ordering conflicts span modules, and the thread-modules
    # set comes from config, not the path arguments.
    wants_concurrency = any(r in CONCURRENCY_RULES for r in enabled)
    if wants_concurrency and (not args.paths or rules is not None):
        findings.extend(lint_concurrency(root, config=config, rules=enabled))
        findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))

    if args.write_baseline:
        Baseline.write(args.write_baseline, findings)
        if not args.quiet:
            print(
                f"wrote {len(findings)} finding(s) to baseline "
                f"{args.write_baseline}"
            )
        return 0

    grandfathered: list = []
    if args.baseline:
        try:
            bl = Baseline.load(args.baseline)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"error: {e}", file=sys.stderr)
            return 2
        findings, grandfathered = bl.split(findings)

    if args.format == "json":
        print(
            json.dumps(
                {
                    "new": [vars(f) for f in findings],
                    "baselined": len(grandfathered),
                    "files": len(files),
                },
                indent=2,
            )
        )
    elif args.format == "github":
        # GitHub Actions workflow-annotation lines: the runner surfaces each
        # finding inline on the PR diff. Newlines are %0A-escaped per the
        # workflow-command spec.
        for f in findings:
            msg = f.message.replace("%", "%25").replace("\n", "%0A")
            print(
                f"::error file={f.path},line={f.line},"
                f"col={f.col + 1},title={f.rule}::{msg}"
            )
    else:
        for f in findings:
            print(f.render())
    if not args.quiet and args.format == "text":
        base = f" ({len(grandfathered)} baselined)" if args.baseline else ""
        print(
            f"tpusim-lint: {len(findings)} new finding(s) in {len(files)} "
            f"file(s){base}",
            file=sys.stderr,
        )
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
