"""Per-module AST analysis shared by every rule.

One :class:`ModuleAnalysis` is built per file and answers the project-aware
questions the rules need:

  * which function defs are **jit-reachable** — passed to ``jax.jit`` /
    ``lax.scan`` / ``vmap`` / ``shard_map`` / ``pallas_call`` (directly or as
    a decorator), or called — transitively, within the module — from one
    that is;
  * which assigned names are **jitted callables** (``f = jax.jit(g, ...)``),
    with their ``donate_argnums`` positions;
  * which local names hold **tracer values** inside a jit-reachable function
    (annotation-aware taint: parameters annotated with Python scalar types
    are static by this codebase's convention, and ``.shape``/``.dtype``
    reads or host casts assigned to a FRESH name stay static — the taint
    set is a monotone fixpoint over names, so rebinding the *same* name,
    ``x = int(x)``, conservatively keeps ``x`` tainted);
  * which names hold **device values** in host orchestration code (taint
    seeded by ``jnp.*``/``jax.*`` calls and the engine's jitted entry
    points, propagated through containers — the pipelined dispatch path
    hands device flags around in a deque).

The analysis is deliberately per-module and name-based: no imports are
resolved, no types inferred. That keeps it fast, dependency-free and
predictable — cross-module reachability is the configured module lists'
job (``hot_modules``, ``device_modules``), not a whole-program analysis.
"""

from __future__ import annotations

import ast
from dataclasses import dataclass, field

from .config import LintConfig
from .findings import Suppressions

#: Call targets (final dotted component) that make a function argument
#: jit-reachable: its body runs under trace.
_JIT_ENTRY_CALLS = frozenset({
    "jit", "scan", "while_loop", "fori_loop", "cond", "switch", "vmap",
    "pmap", "grad", "value_and_grad", "shard_map", "pallas_call", "checkpoint",
    "remat", "associative_scan", "map",
})

#: Annotations naming static-by-convention Python scalars: a parameter so
#: annotated is a trace-time constant, not a tracer.
_STATIC_ANNOTATIONS = frozenset({"bool", "int", "float", "str", "bytes"})

#: Attribute reads that return static metadata even off a tracer.
_STATIC_ATTRS = frozenset({"shape", "ndim", "dtype", "size", "sharding"})

#: Builtin calls whose result is a concrete host value (JX002 owns whether
#: the *cast itself* was legal; for taint purposes the result is static).
_HOST_CASTS = frozenset({"int", "float", "bool", "len", "isinstance", "str", "repr"})


def dotted_name(node: ast.AST) -> str | None:
    """``jax.random.bits`` / ``self._pipe_chunk`` / ``np`` — or None."""
    parts: list[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def assigned_names(target: ast.AST) -> list[str]:
    """Flat Name targets of an assignment target (tuples unpacked)."""
    if isinstance(target, ast.Name):
        return [target.id]
    if isinstance(target, (ast.Tuple, ast.List)):
        out: list[str] = []
        for elt in target.elts:
            out.extend(assigned_names(elt))
        return out
    if isinstance(target, ast.Starred):
        return assigned_names(target.value)
    return []


@dataclass
class FuncInfo:
    node: ast.FunctionDef | ast.AsyncFunctionDef | ast.Lambda
    name: str
    qualname: str
    jit_entered: bool = False
    #: simple callee names (Name or self.<attr>) this function's body calls.
    callees: set[str] = field(default_factory=set)


@dataclass
class JittedCallable:
    key: str  # bare name or attribute name ("_pipe_chunk")
    line: int
    donate: tuple[int, ...] = ()


class ModuleAnalysis:
    def __init__(self, path: str, source: str, config: LintConfig):
        self.path = path
        self.source = source
        self.config = config
        self.tree = ast.parse(source)
        self.lines = source.splitlines()
        self.suppressions = Suppressions(source)
        self.suppressions.extend_spans(self.tree)
        self.funcs: list[FuncInfo] = []
        self._func_by_name: dict[str, list[FuncInfo]] = {}
        self.jitted: dict[str, JittedCallable] = {}
        self._parents: dict[ast.AST, ast.AST] = {}
        self._collect()

    # -- structure ----------------------------------------------------------

    def _collect(self) -> None:
        for parent in ast.walk(self.tree):
            for child in ast.iter_child_nodes(parent):
                self._parents[child] = parent
        # Function defs with qualified names.
        def visit(node: ast.AST, prefix: str) -> None:
            for child in ast.iter_child_nodes(node):
                if isinstance(child, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    qn = f"{prefix}{child.name}"
                    info = FuncInfo(child, child.name, qn)
                    self.funcs.append(info)
                    self._func_by_name.setdefault(child.name, []).append(info)
                    visit(child, qn + ".")
                elif isinstance(child, ast.ClassDef):
                    visit(child, f"{prefix}{child.name}.")
                else:
                    visit(child, prefix)
        visit(self.tree, "")
        for info in self.funcs:
            info.callees = self._callee_names(info.node)
        self._find_jit_entries()
        self._find_jitted_callables()

    def _callee_names(self, func: ast.AST) -> set[str]:
        out: set[str] = set()
        for node in self._walk_own(func):
            if isinstance(node, ast.Call):
                name = dotted_name(node.func)
                if name is None:
                    continue
                if name.startswith("self."):
                    out.add(name.split(".", 1)[1])
                elif "." not in name:
                    out.add(name)
        return out

    def _walk_own(self, func: ast.AST):
        """Walk a function's body including nested defs (closures share the
        trace context) — the caller decides whether that matters."""
        yield from ast.walk(func)

    def enclosing_function(self, node: ast.AST) -> ast.AST | None:
        cur = self._parents.get(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
        ):
            cur = self._parents.get(cur)
        return cur

    def inside_loop(self, node: ast.AST, *, comprehensions: bool = True) -> bool:
        """Is ``node`` lexically inside a For/While (or comprehension) body,
        without crossing a function-def boundary (a nested def's body is its
        own execution context, entered per call, not per iteration)?"""
        cur = self._parents.get(node)
        while cur is not None:
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return True
            if comprehensions and isinstance(
                cur, (ast.ListComp, ast.SetComp, ast.DictComp, ast.GeneratorExp)
            ):
                return True
            if isinstance(cur, (ast.FunctionDef, ast.AsyncFunctionDef)):
                return False
            cur = self._parents.get(cur)
        return False

    def own_nodes(self, func: ast.AST):
        """Walk a function's body WITHOUT descending into nested function
        defs — each nested def has its own FuncInfo and is analyzed in its
        own scope (a same-named local in a sibling closure is a different
        binding, not a reuse)."""
        stack = list(ast.iter_child_nodes(func))
        while stack:
            node = stack.pop()
            yield node
            if not isinstance(
                node, (ast.FunctionDef, ast.AsyncFunctionDef, ast.Lambda)
            ):
                stack.extend(ast.iter_child_nodes(node))

    def branch_arms(self, node: ast.AST, stop: ast.AST) -> list[tuple[int, bool]]:
        """The (If-statement id, in-else-arm) chain from ``node`` up to
        ``stop``: two nodes are mutually exclusive when some shared If places
        them in different arms — an if/else that consumes the same key once
        per path is NOT a reuse."""
        arms: list[tuple[int, bool]] = []
        child, cur = node, self._parents.get(node)
        while cur is not None and cur is not stop:
            if isinstance(cur, ast.If):
                # ``child`` is the If's immediate child on the parent chain:
                # one of test / body stmts / orelse stmts.
                arms.append((id(cur), any(child is n for n in cur.orelse)))
            child, cur = cur, self._parents.get(cur)
        return arms

    def mutually_exclusive(self, a: ast.AST, b: ast.AST, scope: ast.AST) -> bool:
        arms_a = dict(self.branch_arms(a, scope))
        return any(
            if_id in arms_a and arms_a[if_id] != in_else
            for if_id, in_else in self.branch_arms(b, scope)
        )

    def enclosing_loop(self, node: ast.AST) -> ast.AST | None:
        """Nearest enclosing For/While statement within the same function."""
        cur = self._parents.get(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            if isinstance(cur, (ast.For, ast.AsyncFor, ast.While)):
                return cur
            cur = self._parents.get(cur)
        return None

    def loop_targets(self, node: ast.AST) -> set[str]:
        """Names bound by For-loop targets enclosing ``node`` (same function)."""
        out: set[str] = set()
        cur = self._parents.get(node)
        while cur is not None and not isinstance(
            cur, (ast.FunctionDef, ast.AsyncFunctionDef)
        ):
            if isinstance(cur, (ast.For, ast.AsyncFor)):
                out.update(assigned_names(cur.target))
            cur = self._parents.get(cur)
        return out

    # -- jit reachability ---------------------------------------------------

    def _mark_entry(self, name: str) -> None:
        for info in self._func_by_name.get(name, []):
            info.jit_entered = True

    def _find_jit_entries(self) -> None:
        for node in ast.walk(self.tree):
            if isinstance(node, ast.Call):
                callee = dotted_name(node.func)
                if callee and callee.rsplit(".", 1)[-1] in _JIT_ENTRY_CALLS:
                    for arg in list(node.args) + [kw.value for kw in node.keywords]:
                        ref = dotted_name(arg)
                        if ref is None:
                            continue
                        self._mark_entry(ref.rsplit(".", 1)[-1])
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    names: list[str] = []
                    d = dotted_name(dec)
                    if d:
                        names.append(d)
                    if isinstance(dec, ast.Call):
                        d = dotted_name(dec.func)
                        if d:
                            names.append(d)
                        for a in dec.args:  # partial(jax.jit, ...)
                            d = dotted_name(a)
                            if d:
                                names.append(d)
                    if any(n.rsplit(".", 1)[-1] in _JIT_ENTRY_CALLS for n in names):
                        self._mark_entry(node.name)
        # Transitive closure over the in-module call graph.
        changed = True
        while changed:
            changed = False
            entered = {f.name for f in self.funcs if f.jit_entered}
            for info in self.funcs:
                if info.jit_entered:
                    for callee in info.callees:
                        if callee not in entered:
                            self._mark_entry(callee)
                            if any(
                                f.jit_entered
                                for f in self._func_by_name.get(callee, [])
                            ):
                                changed = True

    def jit_entered_functions(self) -> list[FuncInfo]:
        return [f for f in self.funcs if f.jit_entered]

    # -- jitted-callable registry -------------------------------------------

    @staticmethod
    def _donate_positions(call: ast.Call) -> tuple[int, ...]:
        for kw in call.keywords:
            if kw.arg == "donate_argnums":
                v = kw.value
                if isinstance(v, ast.Constant) and isinstance(v.value, int):
                    return (v.value,)
                if isinstance(v, (ast.Tuple, ast.List)):
                    return tuple(
                        e.value
                        for e in v.elts
                        if isinstance(e, ast.Constant) and isinstance(e.value, int)
                    )
        return ()

    @staticmethod
    def _as_jit_call(call: ast.Call) -> ast.Call | None:
        """The Call carrying jit's keywords, if this expression is one:
        ``jax.jit(...)`` itself, or ``partial(jax.jit, donate_argnums=...)``
        — whose keywords jit receives verbatim on application, so
        ``donate_argnums`` sits on the partial call."""
        fn = dotted_name(call.func)
        leaf = fn.rsplit(".", 1)[-1] if fn else None
        if leaf == "jit":
            return call
        if leaf == "partial" and any(
            (dotted_name(a) or "").rsplit(".", 1)[-1] == "jit" for a in call.args
        ):
            return call
        return None

    def _find_jitted_callables(self) -> None:
        for node in ast.walk(self.tree):
            call: ast.Call | None = None
            keys: list[str] = []
            if isinstance(node, ast.Assign) and isinstance(node.value, ast.Call):
                call = self._as_jit_call(node.value)
                for tgt in node.targets:
                    if isinstance(tgt, ast.Name):
                        keys.append(tgt.id)
                    elif isinstance(tgt, ast.Attribute):
                        keys.append(tgt.attr)
            elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        jc = self._as_jit_call(dec)
                        if jc is not None:
                            call, keys = jc, [node.name]
                    elif (dotted_name(dec) or "").rsplit(".", 1)[-1] == "jit":
                        # Bare ``@jax.jit``: jitted with no donate_argnums —
                        # still a JX006 target.
                        self.jitted[node.name] = JittedCallable(
                            node.name, dec.lineno, ()
                        )
            if call is None:
                continue
            donate = self._donate_positions(call)
            for key in keys:
                self.jitted[key] = JittedCallable(key, call.lineno, donate)

    def resolve_jitted(self, func_expr: ast.AST) -> JittedCallable | None:
        """The registry entry a call target refers to (bare name or final
        attribute name), if any."""
        name = dotted_name(func_expr)
        if name is None:
            return None
        return self.jitted.get(name.rsplit(".", 1)[-1])

    # -- taint --------------------------------------------------------------

    def tracer_tainted_names(self, info: FuncInfo) -> set[str]:
        """Names holding tracer values inside a jit-reachable function:
        parameters (minus self/cls and static-annotated scalars) plus
        everything assigned from jax/jnp math or tainted operands."""
        node = info.node
        tainted: set[str] = set()
        args = node.args
        all_params = (
            list(args.posonlyargs) + list(args.args) + list(args.kwonlyargs)
        )
        for a in all_params:
            if a.arg in ("self", "cls"):
                continue
            ann = a.annotation
            ann_name = None
            if isinstance(ann, ast.Name):
                ann_name = ann.id
            elif isinstance(ann, ast.Constant) and isinstance(ann.value, str):
                ann_name = ann.value.split("|")[0].strip()
            if ann_name in _STATIC_ANNOTATIONS:
                continue
            tainted.add(a.arg)
        if args.vararg:
            tainted.add(args.vararg.arg)
        if args.kwarg:
            tainted.add(args.kwarg.arg)

        def expr_tainted(e: ast.AST) -> bool:
            return _expr_tainted(e, tainted)

        changed = True
        while changed:
            changed = False
            for sub in ast.walk(node):
                new_names: list[str] = []
                if isinstance(sub, ast.Assign):
                    if expr_tainted(sub.value):
                        new_names = [
                            n for t in sub.targets for n in assigned_names(t)
                        ]
                elif isinstance(sub, (ast.AugAssign, ast.AnnAssign)):
                    if sub.value is not None and expr_tainted(sub.value):
                        new_names = assigned_names(sub.target)
                elif isinstance(sub, ast.NamedExpr):
                    if expr_tainted(sub.value):
                        new_names = assigned_names(sub.target)
                elif isinstance(sub, (ast.For, ast.AsyncFor)):
                    new_names = _for_target_taint(sub.target, sub.iter, expr_tainted)
                for name in new_names:
                    if name not in tainted:
                        tainted.add(name)
                        changed = True
        return tainted

    def device_tainted_names(self, func: ast.AST) -> set[str]:
        """Names holding device values in host code: seeded by jnp/jax call
        results and the configured engine entry points, propagated through
        assignment, arithmetic and container append/pop."""
        patterns = set(self.config.device_call_patterns)
        tainted: set[str] = set()

        def seeds_device(call: ast.Call) -> bool:
            name = dotted_name(call.func)
            if name is None:
                return False
            root, leaf = name.split(".", 1)[0], name.rsplit(".", 1)[-1]
            if root in ("jnp", "jax") and leaf not in _HOST_CASTS:
                return True
            return leaf in patterns

        def expr_tainted(e: ast.AST) -> bool:
            return structural_taint(e, tainted, seed_call=seeds_device)

        changed = True
        while changed:
            changed = False
            for sub in ast.walk(func):
                new: list[str] = []
                if isinstance(sub, ast.Assign) and expr_tainted(sub.value):
                    new = [n for t in sub.targets for n in assigned_names(t)]
                elif (
                    isinstance(sub, (ast.AugAssign, ast.NamedExpr))
                    and sub.value is not None
                    and expr_tainted(sub.value)
                ):
                    new = assigned_names(sub.target)
                elif isinstance(sub, ast.Call):
                    # container.append(device_value) taints the container.
                    name = dotted_name(sub.func)
                    if (
                        name
                        and "." in name
                        and name.rsplit(".", 1)[-1] in ("append", "appendleft", "extend", "add")
                        and any(expr_tainted(a) for a in sub.args)
                    ):
                        new = [name.split(".", 1)[0]]
                for n in new:
                    if n not in tainted:
                        tainted.add(n)
                        changed = True
        return tainted

def structural_taint(e: ast.AST, tainted: set[str], seed_call=None) -> bool:
    """Device-value taint of one expression, structural: device-ness flows
    through *reads* (attributes, subscripts, calls on a tainted object,
    collections containing one) but NOT through passing a tainted value as an
    argument to an unknown function — whose return is usually host-side (the
    runner's finalize/retry helpers return numpy). ``seed_call`` optionally
    marks calls whose results are device values (jnp/jax + the configured
    engine entry points); the JX002 sync-site check omits it, asking only
    whether an already-tainted name flows in."""
    if isinstance(e, ast.Name):
        return isinstance(e.ctx, ast.Load) and e.id in tainted
    if isinstance(e, ast.Call):
        if seed_call is not None and seed_call(e):
            return True
        return structural_taint(e.func, tainted, seed_call)
    if isinstance(e, (ast.Attribute, ast.Starred)):
        return structural_taint(e.value, tainted, seed_call)
    if isinstance(e, ast.Subscript):
        return structural_taint(e.value, tainted, seed_call)
    if isinstance(e, ast.BinOp):
        return structural_taint(e.left, tainted, seed_call) or structural_taint(
            e.right, tainted, seed_call
        )
    if isinstance(e, ast.UnaryOp):
        return structural_taint(e.operand, tainted, seed_call)
    if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
        return any(structural_taint(v, tainted, seed_call) for v in e.elts)
    if isinstance(e, ast.Compare):
        return structural_taint(e.left, tainted, seed_call) or any(
            structural_taint(c, tainted, seed_call) for c in e.comparators
        )
    if isinstance(e, ast.BoolOp):
        return any(structural_taint(v, tainted, seed_call) for v in e.values)
    if isinstance(e, ast.IfExp):
        return any(
            structural_taint(v, tainted, seed_call)
            for v in (e.test, e.body, e.orelse)
        )
    return False


def _for_target_taint(target: ast.AST, it: ast.AST, expr_tainted) -> list[str]:
    """Names a For loop taints, structure-aware: iterating ``d.items()``
    yields static keys and tainted values, ``zip(a, b)`` taints per argument,
    ``enumerate(x)`` never taints the counter, ``range(...)`` taints nothing.
    Everything else falls back to all-or-nothing on the iterable's taint."""
    names = assigned_names(target)
    call_name = dotted_name(it.func) if isinstance(it, ast.Call) else None
    leaf = call_name.rsplit(".", 1)[-1] if call_name else None
    elts = target.elts if isinstance(target, (ast.Tuple, ast.List)) else None
    if leaf == "range":
        return []
    if leaf == "keys":
        return []
    if leaf == "items" and elts is not None and len(elts) == 2:
        assert isinstance(it, ast.Call)
        return assigned_names(elts[1]) if expr_tainted(it.func) else []
    if (
        leaf == "zip"
        and elts is not None
        and isinstance(it, ast.Call)
        and len(elts) == len(it.args)
    ):
        out: list[str] = []
        for elt, arg in zip(elts, it.args):
            if expr_tainted(arg):
                out.extend(assigned_names(elt))
        return out
    if (
        leaf == "enumerate"
        and elts is not None
        and len(elts) == 2
        and isinstance(it, ast.Call)
        and it.args
    ):
        return assigned_names(elts[1]) if expr_tainted(it.args[0]) else []
    return names if expr_tainted(it) else []


def _expr_tainted(e: ast.AST, tainted: set[str]) -> bool:
    """Tracer taint of one expression (JX001): conservative, but static
    metadata reads, host casts and None-comparisons launder."""
    if isinstance(e, ast.Name):
        return isinstance(e.ctx, ast.Load) and e.id in tainted
    if isinstance(e, ast.Constant):
        return False
    if isinstance(e, ast.Attribute):
        if e.attr in _STATIC_ATTRS:
            return False
        return _expr_tainted(e.value, tainted)
    if isinstance(e, ast.Call):
        name = dotted_name(e.func)
        leaf = name.rsplit(".", 1)[-1] if name else ""
        if leaf in _HOST_CASTS:
            return False
        if name is not None:
            root = name.split(".", 1)[0]
            if root in ("jnp",) or root == "jax" or ".lax" in name:
                return True
        return any(
            _expr_tainted(a, tainted)
            for a in list(e.args) + [kw.value for kw in e.keywords]
        ) or _expr_tainted(e.func, tainted)
    if isinstance(e, ast.Compare):
        # ``x is None`` / ``x is not None`` are trace-time-static checks.
        if all(
            isinstance(c, ast.Constant) and c.value is None for c in e.comparators
        ) and all(isinstance(op, (ast.Is, ast.IsNot)) for op in e.ops):
            return False
        return _expr_tainted(e.left, tainted) or any(
            _expr_tainted(c, tainted) for c in e.comparators
        )
    if isinstance(e, (ast.BoolOp, ast.JoinedStr)):
        return any(_expr_tainted(v, tainted) for v in e.values)
    if isinstance(e, ast.BinOp):
        return _expr_tainted(e.left, tainted) or _expr_tainted(e.right, tainted)
    if isinstance(e, ast.UnaryOp):
        return _expr_tainted(e.operand, tainted)
    if isinstance(e, ast.Subscript):
        return _expr_tainted(e.value, tainted) or _expr_tainted(e.slice, tainted)
    if isinstance(e, (ast.Tuple, ast.List, ast.Set)):
        return any(_expr_tainted(v, tainted) for v in e.elts)
    if isinstance(e, ast.IfExp):
        return any(
            _expr_tainted(v, tainted) for v in (e.test, e.body, e.orelse)
        )
    if isinstance(e, ast.Starred):
        return _expr_tainted(e.value, tainted)
    return False
