"""Interprocedural thread-safety pass: JX015-JX019.

The per-module rules (tpusim.lint.rules) pin JAX/device hygiene and the
contract pass (tpusim.lint.contracts) pins the stringly-typed protocols;
this pass pins the repo's *thread populations* — the fleet heartbeat
daemon, the chaos fetch watchdog, the metrics ThreadingHTTPServer and the
bench hard-watchdog — before the `tpusim serve` daemon multiplies them:

  JX015  unsynchronized shared state — an attribute or module global
         written inside a ``threading.Thread(target=...)`` body (or any
         function reachable from one) that is also read or written from
         another execution context with no common lock held at both sites.
         ``with <lock>:`` regions are tracked as dataflow (lock attrs by
         configured name, plus any name assigned from ``threading.Lock()``).
  JX016  thread lifecycle discipline — a non-daemon thread no path ever
         ``join()``s, a ``Thread(...).start()`` whose handle is dropped on
         the floor (unjoinable, unreapable), and a daemon thread whose body
         touches files without the beat-retry ``try/except OSError``
         pattern fleet._Heartbeat established (a daemon dies with the
         process; an unhandled late-write OSError kills it early and
         silently).
  JX017  lock-ordering — two locks acquired nested in both orders anywhere
         across the scanned module set: the classic deadlock lint.
  JX018  blocking call under lock — device dispatch (JX002's device-call
         patterns), subprocess waits, socket accepts, sleeps, and untimed
         ``queue.get()`` inside a held-lock region.
  JX019  fork-after-threads / signal-handler safety — ``subprocess`` or
         ``os.fork`` spawns from *thread context* (the forked child
         inherits whatever locks other threads held — instant deadlock),
         ``os.fork`` anywhere in a module that starts threads, and
         non-async-signal-safe work (lock acquisition, queue ops, joins)
         reachable from a ``signal.signal`` handler.

Like the contract pass this is whole-project, AST/text only and jax-free:
it reads ``thread-modules`` from ``[tool.tpusim-lint]``, only runs on the
full-walk CLI invocation, honors ``# tpusim-lint: disable=`` comments and
rides the same baseline fingerprints. The analysis is deliberately shallow
where shallow is sound (one- and two-level call chains, module-local lock
identity) and conservative where the bug class is silent — a false
positive here costs one reasoned suppression; a missed data race costs a
wedged serve daemon at 3am.
"""

from __future__ import annotations

import ast
from pathlib import Path
from typing import Callable, Iterator

from .analysis import dotted_name
from .config import LintConfig
from .contracts import ModuleFacts
from .findings import Finding

#: Callable dotted names recognized as thread constructors.
_THREAD_CTORS = frozenset({"threading.Thread", "Thread"})

#: ... as lock constructors (JX015/JX017/JX018 lock identity).
_LOCK_CTORS = frozenset({
    "threading.Lock", "threading.RLock", "Lock", "RLock",
    "threading.Condition", "Condition",
})

#: ... as queue constructors (the untimed-get arm of JX018).
_QUEUE_CTORS = frozenset({
    "queue.Queue", "queue.SimpleQueue", "queue.LifoQueue",
    "queue.PriorityQueue", "Queue", "SimpleQueue",
})

#: Process-spawn call names for JX019.
_SPAWN_CALLS = frozenset({
    "subprocess.run", "subprocess.Popen", "subprocess.call",
    "subprocess.check_call", "subprocess.check_output", "os.system",
    "os.fork", "os.forkpty", "os.posix_spawn", "os.spawnv", "os.spawnvp",
})

#: True fork (no exec) — undefined behavior after threads exist at all.
_FORK_CALLS = frozenset({"os.fork", "os.forkpty"})

#: File-touching call leaves a daemon-thread body must wrap in the
#: beat-retry pattern (JX016): ``try: <write> except OSError: continue``.
_FILE_OP_LEAVES = frozenset({
    "open", "write_text", "write_bytes", "append_jsonl_line",
})
_FILE_OP_DOTTED = frozenset({
    "os.replace", "os.rename", "os.remove", "os.unlink", "os.makedirs",
})

#: Exception names that count as catching an OSError (the beat-retry arm).
_OSERROR_CATCHERS = frozenset({
    "OSError", "IOError", "Exception", "BaseException",
})


def _leaf(dotted: str | None) -> str | None:
    return dotted.rsplit(".", 1)[-1] if dotted else None


def _const_bool(node: ast.AST | None) -> bool | None:
    if isinstance(node, ast.Constant) and isinstance(node.value, bool):
        return node.value
    return None


def _has_timeout(call: ast.Call) -> bool:
    """Timed variants of .wait()/.get() are bounded, not deadlock fuel."""
    if any(kw.arg == "timeout" for kw in call.keywords):
        return True
    return any(
        isinstance(a, ast.Constant) and isinstance(a.value, (int, float))
        for a in call.args
    )


class _Func:
    """One function (or method, or nested def) in the scope tree."""

    __slots__ = ("key", "node", "cls", "parent", "children", "globals")

    def __init__(self, key, node, cls, parent):
        self.key = key
        self.node = node
        self.cls = cls          # owning class name, "" for plain functions
        self.parent = parent    # enclosing function key, None at module level
        self.children: dict[str, str] = {}  # local def name -> key
        self.globals: set[str] = {
            n for stmt in ast.walk(node)
            if isinstance(stmt, ast.Global) for n in stmt.names
        }


class _Access:
    """One attribute/global access or call site, with the lock state."""

    __slots__ = ("node", "held", "protected")

    def __init__(self, node, held, protected):
        self.node = node
        self.held = held            # frozenset of canonical lock ids
        self.protected = protected  # inside try/except-OSError in this func


class _Spawn:
    """One ``threading.Thread(...)`` construction site."""

    __slots__ = ("node", "scope", "target_key", "target_leaf", "daemon",
                 "handles", "binding", "name")

    def __init__(self, node, scope):
        self.node = node
        self.scope = scope
        self.target_key: str | None = None
        self.target_leaf: str | None = None
        self.daemon: bool | None = None
        self.handles: list[str] = []   # canonical ids the handle is bound to
        self.binding = "escaped"       # bound | dropped-start | dropped | escaped
        self.name: str | None = None


class _ModuleThreads:
    """Per-module thread/lock facts: scope tree, call graph, spawns,
    lock-annotated accesses, and the thread-context reachability closure."""

    def __init__(self, facts: ModuleFacts, config: LintConfig):
        self.facts = facts
        self.config = config
        self.funcs: dict[str, _Func] = {}
        self.top: dict[str, str] = {}       # module-level def name -> key
        self.edges: dict[str | None, set[str]] = {}
        self.locks: set[str] = set()        # canonical ids assigned Lock()
        self.queues: set[str] = set()       # canonical ids assigned Queue()
        self.spawns: list[_Spawn] = []
        self.joins: set[str] = set()        # canonical join() receivers
        self.daemon_sets: set[str] = set()  # handles with `X.daemon = True`
        #: scope key (None = module level) -> collected accesses
        self.attr_loads: dict[str | None, list[tuple[tuple, _Access]]] = {}
        self.attr_stores: dict[str | None, list[tuple[tuple, _Access]]] = {}
        self.calls: dict[str | None, list[tuple[str | None, _Access]]] = {}
        self.lock_enters: dict[str | None, list[tuple[str, ast.AST]]] = {}
        self.order_edges: list[tuple[str, str, ast.AST]] = []
        self.signal_handlers: list[tuple[str, ast.AST]] = []
        self._parents: dict[ast.AST, ast.AST] = {}
        for n in ast.walk(facts.tree):
            for c in ast.iter_child_nodes(n):
                self._parents[c] = n
        self._index(facts.tree.body, cls="", parent=None)
        self._collect_lock_assigns()
        for key in [None, *self.funcs]:
            self._scan(key)
        self._collect_spawns()
        self.thread_reach = self._closure(
            {s.target_key for s in self.spawns if s.target_key}
        )
        # "Other execution context": the module level plus everything
        # reachable from a function that is NOT thread-only. __init__ is
        # exempt — publication-before-start is the safe idiom.
        other_seeds = {
            k for k in self.funcs
            if k not in self.thread_reach and _leaf(k) != "__init__"
        }
        self.other_reach = self._closure(other_seeds)

    # -- scope tree -------------------------------------------------------

    def _index(self, body, cls, parent):
        for node in body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                if parent is not None:
                    key = f"{parent}.{node.name}"
                elif cls:
                    key = f"{cls}.{node.name}"
                else:
                    key = node.name
                f = _Func(key, node, cls, parent)
                self.funcs[key] = f
                if parent is not None:
                    self.funcs[parent].children[node.name] = key
                else:
                    self.top.setdefault(node.name, key)
                self._index(node.body, cls=cls, parent=key)
            elif isinstance(node, ast.ClassDef) and parent is None and not cls:
                self._index(node.body, cls=node.name, parent=None)

    def _resolve(self, expr: ast.AST, scope: str | None) -> str | None:
        """A callable reference -> function key, via the lexical chain."""
        if isinstance(expr, ast.Name):
            k = scope
            while k is not None:
                f = self.funcs[k]
                if expr.id in f.children:
                    return f.children[expr.id]
                k = f.parent
            return self.top.get(expr.id)
        d = dotted_name(expr)
        if d and d.startswith("self.") and "." not in d[5:]:
            cls = self.funcs[scope].cls if scope else ""
            if cls and f"{cls}.{d[5:]}" in self.funcs:
                return f"{cls}.{d[5:]}"
        return None

    def _canon(self, expr: ast.AST, scope: str | None) -> str | None:
        """Canonical dotted id: ``self._lock`` in class C -> ``C._lock``."""
        d = dotted_name(expr)
        if d is None:
            return None
        if d.startswith("self."):
            cls = self.funcs[scope].cls if scope else ""
            if cls:
                return f"{cls}.{d[5:]}"
        return d

    def _closure(self, seeds: set[str]) -> set[str]:
        out, work = set(seeds), list(seeds)
        while work:
            for callee in self.edges.get(work.pop(), ()):
                if callee not in out:
                    out.add(callee)
                    work.append(callee)
        return out

    # -- fact collection --------------------------------------------------

    def _collect_lock_assigns(self):
        for node in ast.walk(self.facts.tree):
            if not isinstance(node, ast.Assign) or not isinstance(
                node.value, ast.Call
            ):
                continue
            ctor = dotted_name(node.value.func)
            scope = self._enclosing_scope(node)
            for tgt in node.targets:
                cid = self._canon(tgt, scope)
                if cid is None:
                    continue
                if ctor in _LOCK_CTORS:
                    self.locks.add(cid)
                elif ctor in _QUEUE_CTORS:
                    self.queues.add(cid)

    def _enclosing_scope(self, node: ast.AST) -> str | None:
        n = self._parents.get(node)
        while n is not None:
            if isinstance(n, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for key, f in self.funcs.items():
                    if f.node is n:
                        return key
            n = self._parents.get(n)
        return None

    def _lock_id(self, expr: ast.AST, scope: str | None) -> str | None:
        cid = self._canon(expr, scope)
        if cid is None:
            return None
        if _leaf(cid) in self.config.lock_attr_names or cid in self.locks:
            return cid
        return None

    def _scan(self, key: str | None):
        self.attr_loads[key] = []
        self.attr_stores[key] = []
        self.calls[key] = []
        self.lock_enters[key] = []
        self.edges[key] = set()
        if key is None:
            body = [
                n for n in self.facts.tree.body
                if not isinstance(
                    n, (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef)
                )
            ]
        else:
            body = self.funcs[key].node.body
        for stmt in body:
            self._scan_node(stmt, key, frozenset(), False)

    def _scan_node(self, node, key, held, protected):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            return  # separate scope; its own _scan pass covers it
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = set(held)
            for item in node.items:
                self._scan_node(item.context_expr, key, held, protected)
                lid = self._lock_id(item.context_expr, key)
                if lid is not None:
                    for h in inner:
                        if h != lid:
                            self.order_edges.append((h, lid, item.context_expr))
                    inner.add(lid)
                    self.lock_enters[key].append((lid, item.context_expr))
            for stmt in node.body:
                self._scan_node(stmt, key, frozenset(inner), protected)
            return
        if isinstance(node, ast.Try):
            catches = any(
                h.type is None
                or _leaf(dotted_name(h.type)) in _OSERROR_CATCHERS
                or (isinstance(h.type, ast.Tuple) and any(
                    _leaf(dotted_name(e)) in _OSERROR_CATCHERS
                    for e in h.type.elts))
                for h in node.handlers
            )
            for stmt in node.body:
                self._scan_node(stmt, key, held, protected or catches)
            for part in (*node.handlers, *node.orelse, *node.finalbody):
                self._scan_node(part, key, held, protected)
            return
        self._visit(node, key, held, protected)
        for child in ast.iter_child_nodes(node):
            self._scan_node(child, key, held, protected)

    def _visit(self, node, key, held, protected):
        acc = lambda: _Access(node, held, protected)  # noqa: E731
        if isinstance(node, ast.Attribute) and isinstance(
            node.value, ast.Name
        ) and node.value.id == "self":
            cls = self.funcs[key].cls if key else ""
            if cls:
                bucket = (
                    self.attr_stores
                    if isinstance(node.ctx, ast.Store)
                    else self.attr_loads
                )
                bucket[key].append(((cls, node.attr), acc()))
        elif isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            if key is not None and node.id in self.funcs[key].globals:
                self.attr_stores[key].append((("", node.id), acc()))
        elif isinstance(node, ast.Call):
            d = dotted_name(node.func)
            self.calls[key].append((d, acc()))
            callee = self._resolve(node.func, key)
            if callee is not None:
                self.edges[key].add(callee)
            if d == "signal.signal" and len(node.args) == 2:
                h = self._resolve(node.args[1], key)
                if h is not None:
                    self.signal_handlers.append((h, node))
            if _leaf(d) == "join" and isinstance(node.func, ast.Attribute):
                recv = self._canon(node.func.value, key)
                if recv is not None:
                    self.joins.add(recv)

    # -- spawns -----------------------------------------------------------

    def _collect_spawns(self):
        for key in [None, *self.funcs]:
            for d, acc in self.calls[key]:
                if d not in _THREAD_CTORS:
                    continue
                call = acc.node
                sp = _Spawn(call, key)
                for kw in call.keywords:
                    if kw.arg == "target":
                        sp.target_key = self._resolve(kw.value, key)
                        sp.target_leaf = _leaf(dotted_name(kw.value))
                    elif kw.arg == "daemon":
                        sp.daemon = _const_bool(kw.value)
                    elif kw.arg == "name":
                        if isinstance(kw.value, ast.Constant):
                            sp.name = str(kw.value.value)
                p = self._parents.get(call)
                if isinstance(p, ast.Assign):
                    sp.binding = "bound"
                    for tgt in p.targets:
                        cid = self._canon(tgt, key)
                        if cid is not None:
                            sp.handles.append(cid)
                elif isinstance(p, ast.Attribute) and p.attr == "start":
                    sp.binding = "dropped-start"
                elif isinstance(p, ast.Expr):
                    sp.binding = "dropped"
                self.spawns.append(sp)
        for node in ast.walk(self.facts.tree):
            if (
                isinstance(node, ast.Assign)
                and isinstance(node.targets[0], ast.Attribute)
                and node.targets[0].attr == "daemon"
                and _const_bool(node.value) is True
            ):
                cid = self._canon(
                    node.targets[0].value, self._enclosing_scope(node)
                )
                if cid is not None:
                    self.daemon_sets.add(cid)

    def spawn_is_daemon(self, sp: _Spawn) -> bool:
        if sp.daemon is not None:
            return sp.daemon
        return any(h in self.daemon_sets for h in sp.handles)

    def _resolve_leafcall(self, d: str, scope: str | None) -> str | None:
        """Re-resolve a *recorded* dotted call string to a function key, so
        _daemon_file_ops can ask "who calls `_write`, and are all of those
        call sites inside a try/except OSError?"."""
        if "." not in d:
            k = scope
            while k is not None:
                f = self.funcs[k]
                if d in f.children:
                    return f.children[d]
                k = f.parent
            return self.top.get(d)
        if d.startswith("self.") and "." not in d[5:] and scope:
            cls = self.funcs[scope].cls
            if cls and f"{cls}.{d[5:]}" in self.funcs:
                return f"{cls}.{d[5:]}"
        return None


class ProjectConcurrency:
    """The JX015-JX019 checks over the configured thread-module set."""

    def __init__(self, root: Path, config: LintConfig):
        self.root = Path(root)
        self.config = config
        self.modules: dict[str, ModuleFacts] = {}
        self.threads: dict[str, _ModuleThreads] = {}
        for rel in config.thread_modules:
            p = self.root / rel
            if not p.exists():
                continue
            try:
                facts = ModuleFacts(rel, p.read_text())
            except SyntaxError:
                continue
            self.modules[rel] = facts
            self.threads[rel] = _ModuleThreads(facts, config)

    # -- JX015 ------------------------------------------------------------

    def check_shared_state(self) -> Iterator[Finding]:
        for rel in sorted(self.threads):
            mt = self.threads[rel]
            if not mt.thread_reach:
                continue
            thread_writes: dict[tuple, list[_Access]] = {}
            other_access: dict[tuple, list[_Access]] = {}
            for key in mt.funcs:
                for attr, acc in mt.attr_stores[key]:
                    if key in mt.thread_reach:
                        thread_writes.setdefault(attr, []).append(acc)
                    if key in mt.other_reach:
                        other_access.setdefault(attr, []).append(acc)
                if key in mt.other_reach:
                    for attr, acc in mt.attr_loads[key]:
                        other_access.setdefault(attr, []).append(acc)
            for attr, acc in [*mt.attr_stores[None], *mt.attr_loads[None]]:
                other_access.setdefault(attr, []).append(acc)
            for attr in sorted(thread_writes):
                if attr[1] in self.config.lock_attr_names:
                    continue  # the lock object itself is the synchronizer
                hit = next(
                    (
                        (w, o)
                        for w in thread_writes[attr]
                        for o in other_access.get(attr, [])
                        if not (w.held & o.held)
                    ),
                    None,
                )
                if hit is None:
                    continue
                w, o = hit
                where = (
                    "the same line runs in both thread and caller context"
                    if o.node is w.node
                    else f"also accessed at line {o.node.lineno}"
                )
                name = f"{attr[0]}.{attr[1]}" if attr[0] else attr[1]
                yield self.modules[rel].finding(
                    "JX015", w.node,
                    f"`{name}` is written from a spawned thread and {where} "
                    f"with no common lock held — unsynchronized shared "
                    f"state (guard both sites with one lock, or make the "
                    f"write single-context)",
                )

    # -- JX016 ------------------------------------------------------------

    def check_lifecycle(self) -> Iterator[Finding]:
        for rel in sorted(self.threads):
            mt = self.threads[rel]
            for sp in mt.spawns:
                what = sp.name or sp.target_leaf or "thread"
                if sp.binding == "dropped-start":
                    yield self.modules[rel].finding(
                        "JX016", sp.node,
                        f"`{what}` thread handle dropped at start() — "
                        f"unjoinable and unreapable; bind the Thread object "
                        f"so callers can join or inspect it",
                    )
                    continue
                if sp.binding == "dropped":
                    yield self.modules[rel].finding(
                        "JX016", sp.node,
                        f"`{what}` Thread constructed and discarded — "
                        f"never started, never joinable",
                    )
                    continue
                daemon = mt.spawn_is_daemon(sp)
                if not daemon and sp.binding == "bound" and not any(
                    h in mt.joins or _leaf(h) in {_leaf(j) for j in mt.joins}
                    for h in sp.handles
                ):
                    yield self.modules[rel].finding(
                        "JX016", sp.node,
                        f"non-daemon thread `{what}` is never join()ed on "
                        f"any path — it will outlive shutdown and block "
                        f"interpreter exit",
                    )
                if daemon and sp.target_key is not None:
                    yield from self._daemon_file_ops(rel, mt, sp)

    def _daemon_file_ops(self, rel, mt, sp):
        body = mt._closure({sp.target_key})
        for key in sorted(body):
            for d, acc in mt.calls[key]:
                leaf = _leaf(d)
                if not (leaf in _FILE_OP_LEAVES or d in _FILE_OP_DOTTED):
                    continue
                if acc.protected:
                    continue
                # One level up: protected if every thread-context call site
                # of this function sits in a try/except-OSError (the fleet
                # `_loop` -> `_write` shape).
                callers = [
                    c
                    for ck in body
                    for c in mt.calls[ck]
                    if c[0] is not None
                    and mt._resolve_leafcall(c[0], ck) == key
                ]
                if callers and all(c[1].protected for c in callers):
                    continue
                what = sp.name or sp.target_leaf or "daemon thread"
                yield self.modules[rel].finding(
                    "JX016", acc.node,
                    f"daemon thread `{what}` touches a file via "
                    f"`{leaf}` with no try/except OSError on the write "
                    f"path — a late I/O error kills the daemon silently "
                    f"(use the heartbeat beat-retry pattern)",
                )

    # -- JX017 ------------------------------------------------------------

    def check_lock_order(self) -> Iterator[Finding]:
        first: dict[tuple[str, str], tuple[str, ast.AST]] = {}
        for rel in sorted(self.threads):
            for a, b, node in self.threads[rel].order_edges:
                first.setdefault((a, b), (rel, node))
        done: set[frozenset] = set()
        for (a, b), (rel, node) in sorted(
            first.items(), key=lambda kv: (kv[1][0], kv[1][1].lineno)
        ):
            if (b, a) not in first or frozenset((a, b)) in done:
                continue
            done.add(frozenset((a, b)))
            orel, onode = first[(b, a)]
            yield self.modules[rel].finding(
                "JX017", node,
                f"locks `{a}` and `{b}` are acquired nested in both orders "
                f"(reverse order at {orel}:{onode.lineno}) — inconsistent "
                f"lock ordering deadlocks under contention; pick one "
                f"global order",
            )

    # -- JX018 ------------------------------------------------------------

    def check_blocking_under_lock(self) -> Iterator[Finding]:
        pats = self.config.blocking_call_patterns
        dotted_pats = frozenset(p for p in pats if "." in p)
        leaf_pats = frozenset(p for p in pats if "." not in p)
        dev_pats = self.config.device_call_patterns
        for rel in sorted(self.threads):
            mt = self.threads[rel]
            for key in [None, *mt.funcs]:
                for d, acc in mt.calls[key]:
                    if not acc.held:
                        continue
                    leaf = _leaf(d)
                    if leaf is None:
                        continue
                    lock = sorted(acc.held)[0]
                    call = acc.node
                    if d in dotted_pats or (
                        leaf in leaf_pats
                        and not (
                            leaf in ("wait", "get") and _has_timeout(call)
                        )
                    ):
                        yield self.modules[rel].finding(
                            "JX018", call,
                            f"blocking call `{d}` while holding `{lock}` — "
                            f"every other acquirer stalls behind this I/O; "
                            f"move the call outside the critical section",
                        )
                    elif (
                        leaf == "get"
                        and isinstance(call.func, ast.Attribute)
                        and not _has_timeout(call)
                    ):
                        recv = mt._canon(call.func.value, key)
                        if recv in mt.queues:
                            yield self.modules[rel].finding(
                                "JX018", call,
                                f"untimed `{recv}.get()` while holding "
                                f"`{lock}` — an empty queue parks this "
                                f"thread forever with the lock held",
                            )
                    elif any(p in leaf for p in dev_pats):
                        yield self.modules[rel].finding(
                            "JX018", call,
                            f"device dispatch `{d}` while holding `{lock}` "
                            f"— a compile or transfer stall serializes "
                            f"every thread behind the lock",
                        )

    # -- JX019 ------------------------------------------------------------

    def check_fork_and_signals(self) -> Iterator[Finding]:
        for rel in sorted(self.threads):
            mt = self.threads[rel]
            flagged: set[ast.AST] = set()
            for key in sorted(mt.thread_reach):
                if key not in mt.calls:
                    continue
                for d, acc in mt.calls[key]:
                    if d in _SPAWN_CALLS:
                        flagged.add(acc.node)
                        yield self.modules[rel].finding(
                            "JX019", acc.node,
                            f"process spawn `{d}` from thread context — "
                            f"the child inherits locks other threads hold "
                            f"at fork time; spawn from the main thread "
                            f"(spawn-before-threads ordering)",
                        )
            if mt.spawns:
                for key in [None, *mt.funcs]:
                    for d, acc in mt.calls[key]:
                        if d in _FORK_CALLS and acc.node not in flagged:
                            yield self.modules[rel].finding(
                                "JX019", acc.node,
                                f"`{d}` in a module that starts threads — "
                                f"fork without exec after threads exist is "
                                f"undefined behavior; use subprocess or "
                                f"fork before any Thread.start()",
                            )
            for handler, _reg in mt.signal_handlers:
                for key in sorted(mt._closure({handler})):
                    for lid, node in mt.lock_enters.get(key, ()):
                        yield self.modules[rel].finding(
                            "JX019", node,
                            f"signal handler `{handler}` acquires lock "
                            f"`{lid}` — handlers interrupt arbitrary "
                            f"bytecode, including the holder of that lock "
                            f"(self-deadlock); set an Event or flag "
                            f"instead",
                        )
                    for d, acc in mt.calls.get(key, ()):
                        leaf = _leaf(d)
                        recv = (
                            mt._canon(acc.node.func.value, key)
                            if isinstance(acc.node.func, ast.Attribute)
                            else None
                        )
                        if (
                            leaf in ("acquire", "join")
                            or (leaf in ("get", "put") and recv in mt.queues)
                        ):
                            yield self.modules[rel].finding(
                                "JX019", acc.node,
                                f"non-async-signal-safe call `{d}` "
                                f"reachable from signal handler "
                                f"`{handler}` — handlers may run with "
                                f"that object's internal lock held",
                            )


# ---------------------------------------------------------------------------
# Registry + entry point (mirrors contracts.CONTRACT_RULES).

ConcurrencyFn = Callable[[ProjectConcurrency], Iterator[Finding]]

CONCURRENCY_RULES: dict[str, tuple[ConcurrencyFn, str]] = {
    "JX015": (
        ProjectConcurrency.check_shared_state,
        "attribute/global written in a thread body and touched from "
        "another context with no common lock",
    ),
    "JX016": (
        ProjectConcurrency.check_lifecycle,
        "non-daemon thread never joined; dropped thread handle; daemon "
        "file I/O without the beat-retry OSError guard",
    ),
    "JX017": (
        ProjectConcurrency.check_lock_order,
        "nested lock acquisitions in inconsistent order (deadlock)",
    ),
    "JX018": (
        ProjectConcurrency.check_blocking_under_lock,
        "device dispatch / subprocess wait / untimed queue.get inside a "
        "held-lock region",
    ),
    "JX019": (
        ProjectConcurrency.check_fork_and_signals,
        "fork/subprocess from thread context; non-async-signal-safe work "
        "in signal handlers",
    ),
}


def lint_concurrency(
    root: Path,
    config: LintConfig | None = None,
    rules=None,
) -> list[Finding]:
    """Run the thread-safety rules over the project at ``root``.
    ``rules`` filters to a subset of CONCURRENCY_RULES ids; findings honor
    in-file suppression comments and the shared baseline fingerprints."""
    config = config or LintConfig()
    enabled = [
        r.upper() for r in (rules if rules is not None else config.enabled_rules)
    ]
    wanted = [r for r in enabled if r in CONCURRENCY_RULES]
    if not wanted:
        return []
    ctx = ProjectConcurrency(Path(root), config)
    findings: list[Finding] = []
    seen: set[tuple[str, str, int, int, str]] = set()
    for rule_id in wanted:
        fn, _ = CONCURRENCY_RULES[rule_id]
        for f in fn(ctx):
            key = (f.rule, f.path, f.line, f.col, f.message)
            if key in seen:
                continue
            m = ctx.modules.get(f.path)
            if m is not None and m.suppressions.is_suppressed(f.rule, f.line):
                continue
            seen.add(key)
            findings.append(f)
    findings.sort(key=lambda f: (f.path, f.line, f.col, f.rule))
    return findings
