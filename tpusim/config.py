"""Declarative simulation configuration.

Replaces the reference's compile-time constants and edit-and-recompile roster:
``SIM_DURATION``/``SIM_RUNS`` (reference main.cpp:7-10), ``BLOCK_INTERVAL``/
``PERC_MULTIPLIER``/``SELFISH_ARRIVAL`` (reference simulation.h:16-20) and
``SetupMiners()`` (reference main.cpp:44-65) with plain dataclasses that can be
built in code, loaded from JSON, or driven from the CLI.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

#: Expected time between blocks in seconds (reference simulation.h:16).
BLOCK_INTERVAL_S = 600.0

#: Maps integer percentages in [0, 100] onto [0, uint64::max] for the winner
#: draw thresholds (reference simulation.h:18).
PERC_MULTIPLIER = (2**64 - 1) // 100

#: 12 reference months of 2'629'746 s each, in milliseconds: 365.2425 days
#: (reference main.cpp:7 with std::chrono::months{12}).
DEFAULT_DURATION_MS = 12 * 2_629_746 * 1000

#: Default number of Monte-Carlo runs (reference main.cpp:10).
DEFAULT_RUNS = 16 * 2048

#: ``mode="auto"`` keeps the fast consensus representation only while
#: max_prop/interval stays at or below this. Fast mode's stale-count shortfall
#: needs a compound race, ~ratio^2 per block, so the stale-rate absolute error
#: at the boundary is ~1e-4 — the cross-validation tolerance (BASELINE.json).
#: The reference's 10 s-propagation config (ratio 0.0167) routes to exact; the
#: 1 s default (ratio 0.0017, error ~3e-6) keeps fast.
FAST_MODE_MAX_RACE_RATIO = 0.01


@dataclasses.dataclass(frozen=True)
class MinerConfig:
    """One miner: hashrate share, propagation delay, strategy.

    Mirrors the ``Miner`` constructor parameters (reference simulation.h:57-59):
    integer percent of network hashrate, a binary propagation delay (the time
    before which this miner's blocks have reached nobody and after which they
    have reached everybody), and the optional gamma=0 selfish strategy flag.
    """

    hashrate_pct: int
    propagation_ms: int = 1000
    selfish: bool = False

    def __post_init__(self) -> None:
        if not 0 <= self.hashrate_pct <= 100:
            raise ValueError(f"hashrate_pct must be in [0, 100], got {self.hashrate_pct}")
        if self.propagation_ms < 0:
            raise ValueError(f"propagation_ms must be >= 0, got {self.propagation_ms}")


@dataclasses.dataclass(frozen=True)
class NetworkConfig:
    """The miner roster. Hashrate percentages must sum to 100, as asserted by
    the reference's winner draw (reference simulation.h:220)."""

    miners: tuple[MinerConfig, ...]
    block_interval_s: float = BLOCK_INTERVAL_S

    def __post_init__(self) -> None:
        if not self.miners:
            raise ValueError("network needs at least one miner")
        total = sum(m.hashrate_pct for m in self.miners)
        if total != 100:
            raise ValueError(f"miner hashrate percentages must sum to 100, got {total}")
        if self.block_interval_s <= 0:
            raise ValueError("block_interval_s must be positive")

    @property
    def n_miners(self) -> int:
        return len(self.miners)

    @property
    def any_selfish(self) -> bool:
        return any(m.selfish for m in self.miners)


def default_network(
    propagation_ms: int = 1000,
    selfish_ids: tuple[int, ...] = (),
    hashrates: tuple[int, ...] | None = None,
) -> NetworkConfig:
    """The 9-miner 2025 pool distribution of the reference (main.cpp:44-65):
    30/29/12/11/8/5/3/1/1 percent, homogeneous propagation."""
    if hashrates is None:
        hashrates = (30, 29, 12, 11, 8, 5, 3, 1, 1)
    miners = tuple(
        MinerConfig(hashrate_pct=h, propagation_ms=propagation_ms, selfish=(i in selfish_ids))
        for i, h in enumerate(hashrates)
    )
    return NetworkConfig(miners=miners)


def reference_selfish_network() -> NetworkConfig:
    """The reference's selfish-mining benchmark roster (README.md:89-107,
    main.cpp:44-65 with miner 0 at 40 % and selfish=true): 40 % gamma=0
    selfish miner plus eight honest miners, 1 s propagation. The exact-mode
    production benchmark config shared by bench.py, the hardware sweeps and
    the kernel-equality tests."""
    return default_network(
        propagation_ms=1000, selfish_ids=(0,), hashrates=(40, 19, 12, 11, 8, 5, 3, 1, 1)
    )


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Full simulation configuration: network + duration + run plan.

    ``mode`` selects the consensus-state representation:
      * ``"exact"`` — 3-index common-prefix owner counts; observationally exact
        reorg/stale accounting for every configuration including selfish miners.
      * ``"fast"``  — pairwise counts only. For honest rosters every consensus
        observable (chain contents, blocks found, shares, best height) is
        exact; only the ``stale`` counter is approximate, and it is a provable
        elementwise *lower bound* of the true count (see tpusim.state
        docstring). The shortfall needs a compound-race geometry, probability
        ~ (max_prop/interval)^2 per block, so the stale-*rate* error is below
        the ±1e-4 tolerance whenever that ratio is below ~1e-2.
      * ``"auto"``  — ``exact`` when any miner is selfish or when
        ``max_prop/interval`` exceeds :data:`FAST_MODE_MAX_RACE_RATIO`
        (fast mode's documented accuracy domain), else ``fast``.
    """

    network: NetworkConfig
    duration_ms: int = DEFAULT_DURATION_MS
    runs: int = DEFAULT_RUNS
    seed: int = 0
    #: Runs per device batch. 8192 measured best on v5e (amortizes the
    #: device-loop dispatch; still inside the int32 block-count-sum guard for
    #: year-long runs). The runner clamps to the remaining run count.
    batch_size: int = 8192
    #: In-flight arrival-group buffer slots per (run, miner); None = auto
    #: (2 in both modes — see ``resolved_group_slots`` for the measured
    #: basis; fast mode's accuracy domain caps the race ratio at ~1e-2,
    #: where a third concurrent own-group is a ~(share*ratio)^2 per-block
    #: event: 31 counted overflows in 4.3e8 blocks at the reference
    #: default). Overflow merges the two newest groups, counted in the
    #: reported ``overflow_sum`` diagnostic.
    group_slots: int | None = None
    mode: str = "auto"
    chunk_steps: int | None = None
    #: Events unrolled per device loop iteration (the *superstep* width K).
    #: The per-event RNG word mapping is unchanged for every K — event e of a
    #: chunk always consumes word pair e of that chunk's threefry block — so
    #: K is a pure compile-time performance knob: results are bit-identical
    #: across K and it is NOT part of the sampling identity or checkpoint
    #: fingerprint. None = auto (a measured default; reduced to a divisor of
    #: the resolved chunk_steps / step_block). An explicit K must divide the
    #: resolved chunk_steps (and the Pallas step_block) or the engine raises.
    superstep: int | None = None
    #: Sampling generator. ``"threefry"`` (default): counter-based JAX draws,
    #: order-independent, one (winner, interval) word pair burned per scan
    #: step. ``"xoroshiro"``: the reference's xoroshiro128++ as two sequential
    #: per-run streams (tpusim.xoroshiro), advanced only when a draw is
    #: consumed — bit-compatible with the native backend's generator, so tiny
    #: configs can be A/B-checked draw-for-draw (exactly, with float64 on CPU;
    #: on TPU the uniform->interval mapping is float32-quantized while the
    #: generator words remain bit-exact).
    rng: str = "threefry"
    #: Per-run event flight-recorder ring capacity (tpusim.flight): rows of
    #: packed event records kept on device and exportable as a Perfetto
    #: timeline / JSONL event log (``tpusim trace``). 0 (default) compiles the
    #: recorder out entirely — no extra carried leaves, no extra ops, jitted
    #: programs identical to a recorder-less build. NOT part of the sampling
    #: identity: recording is purely observational.
    flight_capacity: int = 0
    #: Batched wide RNG generation (the tfp.mcmc discipline of vectorizing
    #: the *sampler*, not the loop around it). True (default): the threefry
    #: engines map a chunk's whole (steps, 2) word block to (winner,
    #: interval) draws in ONE vectorized pass before the event loop, and the
    #: xoroshiro path pre-advances both per-run streams K (= superstep) words
    #: per loop iteration, each event selecting its draw by consumption count
    #: — the per-stream word-consumption ORDER is unchanged, so results are
    #: bit-identical to the per-event path and the xoroshiro mode stays
    #: bit-compatible with the native backend. False restores the legacy
    #: per-event draw mapping (kept for A/B timing and bisection). A pure
    #: compile-time performance knob: NOT part of the sampling identity or
    #: checkpoint fingerprint.
    rng_batch: bool = True
    #: Packed-state dtype for the block-COUNT state leaves (heights, stale,
    #: group counts, the consensus count tensors): "auto" (default) packs
    #: them as int16 whenever the per-run Poisson event bound provably fits
    #: (see ``resolved_count_dtype``), halving the scan carry's HBM
    #: round-trip and the Pallas kernel's VMEM residency for those leaves;
    #: "int32" forces the wide layout; "int16" forces packing and FAILS LOUD
    #: (ValueError) when the duration-derived bound does not fit. Time leaves
    #: (clocks, arrivals) always stay int32 — they span 2^30. Values are
    #: identical either way (all arithmetic stays in range), so the dtype is
    #: NOT part of the sampling identity or checkpoint fingerprint.
    state_dtype: str = "auto"

    def __post_init__(self) -> None:
        if self.duration_ms <= 0:
            raise ValueError("duration_ms must be positive")
        if self.runs <= 0:
            raise ValueError("runs must be positive")
        if self.mode not in ("auto", "exact", "fast"):
            raise ValueError(f"mode must be auto|exact|fast, got {self.mode!r}")
        if self.rng not in ("threefry", "xoroshiro"):
            raise ValueError(f"rng must be threefry|xoroshiro, got {self.rng!r}")
        if self.group_slots is not None and self.group_slots < 2:
            raise ValueError("group_slots must be >= 2 (or None for auto)")
        if self.chunk_steps is not None and self.chunk_steps < 1:
            raise ValueError("chunk_steps must be >= 1 (or None for auto)")
        if self.superstep is not None and self.superstep < 1:
            raise ValueError("superstep must be >= 1 (or None for auto)")
        if self.flight_capacity < 0:
            raise ValueError("flight_capacity must be >= 0 (0 disables recording)")
        if self.state_dtype not in ("auto", "int32", "int16"):
            raise ValueError(
                f"state_dtype must be auto|int32|int16, got {self.state_dtype!r}"
            )
        if self.state_dtype == "int16" and not self._count_bound_fits_int16:
            raise ValueError(
                f"state_dtype='int16' requested but the per-run event bound "
                f"({self.count_bound}) exceeds int16 at duration_ms="
                f"{self.duration_ms}; use 'auto' (widens to int32) or shorten "
                f"the duration"
            )
        # 32-bit time-arithmetic envelope (see tpusim.state docstring): one
        # interval draw must stay far below INTERVAL_CAP = 2^27 ms, and
        # propagation delays below one chunk re-base span.
        if self.network.block_interval_s > 3600.0:
            raise ValueError("block_interval_s above 3600 s exceeds the int32 time envelope")
        if any(m.propagation_ms >= 2**24 for m in self.network.miners):
            raise ValueError("propagation_ms must be below 2^24 ms (~4.7 h)")

    @property
    def max_race_ratio(self) -> float:
        """max propagation delay / mean block interval — the per-block race
        probability scale that bounds fast mode's stale-count shortfall."""
        max_prop_ms = max(m.propagation_ms for m in self.network.miners)
        return max_prop_ms / (self.network.block_interval_s * 1000.0)

    @property
    def resolved_group_slots(self) -> int:
        # Auto resolves to 2 in BOTH modes (round 5; exact was 4 through
        # round 4). Measured basis: selfish reveals push their whole burst
        # as ONE merged (arrival, count) group, so deep buffers are unneeded
        # — at 512 runs x 365 d, selfish40 has 0 overflow-merges in 18.1M
        # blocks (statistics identical to K=4) and honest-10s has 192 in
        # 26.6M (stale-rate shift ~1.2e-6, two orders under the ±1e-4
        # criterion) — while K=2 engages the kernels' dense split-slot path
        # and is faster on every measured engine/config (BASELINE.md round-5
        # notes). Overflow merges stay counted in ``overflow_sum``.
        if self.group_slots is not None:
            return self.group_slots
        return 2

    @property
    def count_bound(self) -> int:
        """Upper bound on ANY block-count state value one run can reach: the
        per-run event-loop bound (found + arrival events at mean + 8 sigma of
        the Poisson block count, engine.default_n_steps) — every height /
        group count / consensus-tensor entry is at most the run's total block
        count, which is at most half this, and the ``stale`` counter's
        pathological multi-count geometries stay well inside the remaining
        2x headroom (a popped block can only be re-popped after a
        re-adoption, a ~race_ratio^2 event per block).

        Same formula as ``engine.default_n_steps`` (kept inline so this
        module stays jax-free; pinned equal by tests/test_rng_batch.py)."""
        import math

        mu = self.duration_ms / (self.network.block_interval_s * 1000.0)
        return int(2.0 * (mu + 8.0 * math.sqrt(mu + 1.0))) + 16

    @property
    def _count_bound_fits_int16(self) -> bool:
        return self.count_bound <= 2**15 - 1

    @property
    def resolved_count_dtype(self) -> str:
        """The dtype actually compiled for the block-count state leaves:
        ``state_dtype`` unless "auto", which packs to int16 exactly when
        :attr:`count_bound` fits (~106 days at the 600 s reference interval)
        and widens to int32 otherwise."""
        if self.state_dtype != "auto":
            return self.state_dtype
        return "int16" if self._count_bound_fits_int16 else "int32"

    @property
    def resolved_mode(self) -> str:
        if self.mode != "auto":
            return self.mode
        if self.network.any_selfish or self.max_race_ratio > FAST_MODE_MAX_RACE_RATIO:
            return "exact"
        return "fast"

    def to_json(self) -> str:
        return json.dumps(_config_to_dict(self), indent=2)

    @staticmethod
    def from_json(text: str) -> "SimConfig":
        return _config_from_dict(json.loads(text))


def _config_to_dict(cfg: SimConfig) -> dict[str, Any]:
    return {
        "network": {
            "block_interval_s": cfg.network.block_interval_s,
            "miners": [
                {
                    "hashrate_pct": m.hashrate_pct,
                    "propagation_ms": m.propagation_ms,
                    "selfish": m.selfish,
                }
                for m in cfg.network.miners
            ],
        },
        "duration_ms": cfg.duration_ms,
        "runs": cfg.runs,
        "seed": cfg.seed,
        "batch_size": cfg.batch_size,
        "group_slots": cfg.group_slots,
        "mode": cfg.mode,
        "chunk_steps": cfg.chunk_steps,
        "superstep": cfg.superstep,
        "rng": cfg.rng,
        "flight_capacity": cfg.flight_capacity,
        "rng_batch": cfg.rng_batch,
        "state_dtype": cfg.state_dtype,
    }


def _config_from_dict(d: dict[str, Any]) -> SimConfig:
    net = d["network"]
    miners = tuple(
        MinerConfig(
            hashrate_pct=int(m["hashrate_pct"]),
            propagation_ms=int(m.get("propagation_ms", 1000)),
            selfish=bool(m.get("selfish", False)),
        )
        for m in net["miners"]
    )
    network = NetworkConfig(miners=miners, block_interval_s=float(net.get("block_interval_s", BLOCK_INTERVAL_S)))
    kwargs: dict[str, Any] = {}
    for key in ("duration_ms", "runs", "seed", "batch_size"):
        if key in d:
            kwargs[key] = int(d[key])
    if d.get("group_slots") is not None:
        kwargs["group_slots"] = int(d["group_slots"])
    if d.get("chunk_steps") is not None:
        kwargs["chunk_steps"] = int(d["chunk_steps"])
    if d.get("superstep") is not None:
        kwargs["superstep"] = int(d["superstep"])
    if "mode" in d:
        kwargs["mode"] = str(d["mode"])
    if "flight_capacity" in d:
        kwargs["flight_capacity"] = int(d["flight_capacity"])
    if "rng" in d:
        kwargs["rng"] = str(d["rng"])
    if "rng_batch" in d:
        kwargs["rng_batch"] = bool(d["rng_batch"])
    if "state_dtype" in d:
        kwargs["state_dtype"] = str(d["state_dtype"])
    return SimConfig(network=network, **kwargs)
