"""Declarative simulation configuration.

Replaces the reference's compile-time constants and edit-and-recompile roster:
``SIM_DURATION``/``SIM_RUNS`` (reference main.cpp:7-10), ``BLOCK_INTERVAL``/
``PERC_MULTIPLIER``/``SELFISH_ARRIVAL`` (reference simulation.h:16-20) and
``SetupMiners()`` (reference main.cpp:44-65) with plain dataclasses that can be
built in code, loaded from JSON, or driven from the CLI.
"""

from __future__ import annotations

import dataclasses
import json
from typing import Any

#: Expected time between blocks in seconds (reference simulation.h:16).
BLOCK_INTERVAL_S = 600.0

#: Maps integer percentages in [0, 100] onto [0, uint64::max] for the winner
#: draw thresholds (reference simulation.h:18).
PERC_MULTIPLIER = (2**64 - 1) // 100

#: 12 reference months of 2'629'746 s each, in milliseconds: 365.2425 days
#: (reference main.cpp:7 with std::chrono::months{12}).
DEFAULT_DURATION_MS = 12 * 2_629_746 * 1000

#: Default number of Monte-Carlo runs (reference main.cpp:10).
DEFAULT_RUNS = 16 * 2048

#: ``mode="auto"`` keeps the fast consensus representation only while
#: max_prop/interval stays at or below this. Fast mode's stale-count shortfall
#: needs a compound race, ~ratio^2 per block, so the stale-rate absolute error
#: at the boundary is ~1e-4 — the cross-validation tolerance (BASELINE.json).
#: The reference's 10 s-propagation config (ratio 0.0167) routes to exact; the
#: 1 s default (ratio 0.0017, error ~3e-6) keeps fast.
FAST_MODE_MAX_RACE_RATIO = 0.01

#: One chunk's maximum simulated span in ms (tpusim.state.TIME_CAP as a plain
#: int: this module must stay jax-free, so the value is duplicated here and
#: pinned equal by tests/test_consensus_gather.py). Under ``count_rebase``
#: this horizon, not the full duration, sizes the per-chunk count bound.
TIME_CAP_MS = 2**29

#: The largest ``duration_ms`` whose UN-rebased event bound still fits int16
#: at the 600 s reference interval — the "~106.8 days" every doc cites
#: (= _event_bound(d / 600e3) <= 32767 solved for d; recompute with
#: ``SimConfig.max_int16_duration_ms(count_rebase=False)``).
INT16_MAX_DURATION_MS_600S = 9_230_231_273


@dataclasses.dataclass(frozen=True)
class MinerConfig:
    """One miner: hashrate share, propagation delay, strategy.

    Mirrors the ``Miner`` constructor parameters (reference simulation.h:57-59):
    integer percent of network hashrate, a binary propagation delay (the time
    before which this miner's blocks have reached nobody and after which they
    have reached everybody), and the optional gamma=0 selfish strategy flag.
    """

    hashrate_pct: int
    propagation_ms: int = 1000
    selfish: bool = False

    def __post_init__(self) -> None:
        if not 0 <= self.hashrate_pct <= 100:
            raise ValueError(f"hashrate_pct must be in [0, 100], got {self.hashrate_pct}")
        if self.propagation_ms < 0:
            raise ValueError(f"propagation_ms must be >= 0, got {self.propagation_ms}")


@dataclasses.dataclass(frozen=True)
class NetworkConfig:
    """The miner roster. Hashrate percentages must sum to 100, as asserted by
    the reference's winner draw (reference simulation.h:220)."""

    miners: tuple[MinerConfig, ...]
    block_interval_s: float = BLOCK_INTERVAL_S

    def __post_init__(self) -> None:
        if not self.miners:
            raise ValueError("network needs at least one miner")
        total = sum(m.hashrate_pct for m in self.miners)
        if total != 100:
            raise ValueError(f"miner hashrate percentages must sum to 100, got {total}")
        if self.block_interval_s <= 0:
            raise ValueError("block_interval_s must be positive")

    @property
    def n_miners(self) -> int:
        return len(self.miners)

    @property
    def any_selfish(self) -> bool:
        return any(m.selfish for m in self.miners)


def default_network(
    propagation_ms: int = 1000,
    selfish_ids: tuple[int, ...] = (),
    hashrates: tuple[int, ...] | None = None,
) -> NetworkConfig:
    """The 9-miner 2025 pool distribution of the reference (main.cpp:44-65):
    30/29/12/11/8/5/3/1/1 percent, homogeneous propagation."""
    if hashrates is None:
        hashrates = (30, 29, 12, 11, 8, 5, 3, 1, 1)
    miners = tuple(
        MinerConfig(hashrate_pct=h, propagation_ms=propagation_ms, selfish=(i in selfish_ids))
        for i, h in enumerate(hashrates)
    )
    return NetworkConfig(miners=miners)


def reference_selfish_network() -> NetworkConfig:
    """The reference's selfish-mining benchmark roster (README.md:89-107,
    main.cpp:44-65 with miner 0 at 40 % and selfish=true): 40 % gamma=0
    selfish miner plus eight honest miners, 1 s propagation. The exact-mode
    production benchmark config shared by bench.py, the hardware sweeps and
    the kernel-equality tests."""
    return default_network(
        propagation_ms=1000, selfish_ids=(0,), hashrates=(40, 19, 12, 11, 8, 5, 3, 1, 1)
    )


@dataclasses.dataclass(frozen=True)
class SimConfig:
    """Full simulation configuration: network + duration + run plan.

    ``mode`` selects the consensus-state representation:
      * ``"exact"`` — 3-index common-prefix owner counts; observationally exact
        reorg/stale accounting for every configuration including selfish miners.
      * ``"fast"``  — pairwise counts only. For honest rosters every consensus
        observable (chain contents, blocks found, shares, best height) is
        exact; only the ``stale`` counter is approximate, and it is a provable
        elementwise *lower bound* of the true count (see tpusim.state
        docstring). The shortfall needs a compound-race geometry, probability
        ~ (max_prop/interval)^2 per block, so the stale-*rate* error is below
        the ±1e-4 tolerance whenever that ratio is below ~1e-2.
      * ``"auto"``  — ``exact`` when any miner is selfish or when
        ``max_prop/interval`` exceeds :data:`FAST_MODE_MAX_RACE_RATIO`
        (fast mode's documented accuracy domain), else ``fast``.
    """

    network: NetworkConfig
    duration_ms: int = DEFAULT_DURATION_MS
    runs: int = DEFAULT_RUNS
    seed: int = 0
    #: Runs per device batch. 8192 measured best on v5e (amortizes the
    #: device-loop dispatch; still inside the int32 block-count-sum guard for
    #: year-long runs). The runner clamps to the remaining run count.
    batch_size: int = 8192
    #: In-flight arrival-group buffer slots per (run, miner); None = auto
    #: (2 in both modes — see ``resolved_group_slots`` for the measured
    #: basis; fast mode's accuracy domain caps the race ratio at ~1e-2,
    #: where a third concurrent own-group is a ~(share*ratio)^2 per-block
    #: event: 31 counted overflows in 4.3e8 blocks at the reference
    #: default). Overflow merges the two newest groups, counted in the
    #: reported ``overflow_sum`` diagnostic.
    group_slots: int | None = None
    mode: str = "auto"
    chunk_steps: int | None = None
    #: Events unrolled per device loop iteration (the *superstep* width K).
    #: The per-event RNG word mapping is unchanged for every K — event e of a
    #: chunk always consumes word pair e of that chunk's threefry block — so
    #: K is a pure compile-time performance knob: results are bit-identical
    #: across K and it is NOT part of the sampling identity or checkpoint
    #: fingerprint. None = auto (a measured default; reduced to a divisor of
    #: the resolved chunk_steps / step_block). An explicit K must divide the
    #: resolved chunk_steps (and the Pallas step_block) or the engine raises.
    superstep: int | None = None
    #: Sampling generator. ``"threefry"`` (default): counter-based JAX draws,
    #: order-independent, one (winner, interval) word pair burned per scan
    #: step. ``"xoroshiro"``: the reference's xoroshiro128++ as two sequential
    #: per-run streams (tpusim.xoroshiro), advanced only when a draw is
    #: consumed — bit-compatible with the native backend's generator, so tiny
    #: configs can be A/B-checked draw-for-draw (exactly, with float64 on CPU;
    #: on TPU the uniform->interval mapping is float32-quantized while the
    #: generator words remain bit-exact).
    rng: str = "threefry"
    #: Per-run event flight-recorder ring capacity (tpusim.flight): rows of
    #: packed event records kept on device and exportable as a Perfetto
    #: timeline / JSONL event log (``tpusim trace``). 0 (default) compiles the
    #: recorder out entirely — no extra carried leaves, no extra ops, jitted
    #: programs identical to a recorder-less build. NOT part of the sampling
    #: identity: recording is purely observational.
    flight_capacity: int = 0
    #: Batched wide RNG generation (the tfp.mcmc discipline of vectorizing
    #: the *sampler*, not the loop around it). True (default): the threefry
    #: engines map a chunk's whole (steps, 2) word block to (winner,
    #: interval) draws in ONE vectorized pass before the event loop, and the
    #: xoroshiro path pre-advances both per-run streams K (= superstep) words
    #: per loop iteration, each event selecting its draw by consumption count
    #: — the per-stream word-consumption ORDER is unchanged, so results are
    #: bit-identical to the per-event path and the xoroshiro mode stays
    #: bit-compatible with the native backend. False restores the legacy
    #: per-event draw mapping (kept for A/B timing and bisection). A pure
    #: compile-time performance knob: NOT part of the sampling identity or
    #: checkpoint fingerprint.
    rng_batch: bool = True
    #: Packed-state dtype for the block-COUNT state leaves (heights, stale,
    #: group counts, the consensus count tensors): "auto" (default) packs
    #: them as int16 whenever the per-run Poisson event bound provably fits
    #: (see ``resolved_count_dtype``), halving the scan carry's HBM
    #: round-trip and the Pallas kernel's VMEM residency for those leaves;
    #: "int32" forces the wide layout; "int16" forces packing and FAILS LOUD
    #: (ValueError) when the duration-derived bound does not fit. Time leaves
    #: (clocks, arrivals) always stay int32 — they span 2^30. Values are
    #: identical either way (all arithmetic stays in range), so the dtype is
    #: NOT part of the sampling identity or checkpoint fingerprint.
    state_dtype: str = "auto"
    #: Miner-axis gathers for the consensus sweep (default on): the per-event
    #: one-hot contract-and-sum reads of the best-chain owner's rows
    #: (``own_cp[:, b]``, ``own_in[b, :]``, ``cp[b, :, :]`` — O(M^3) MACs to
    #: read one (M, M) plane) are replaced by dynamic miner-axis indexing on
    #: the winner index ``_best_chain`` already computes (O(M^2) moves).
    #: Values are identical — the same entries are read either way — so the
    #: knob is NOT part of the sampling identity or checkpoint fingerprint;
    #: False restores the legacy one-hot path for A/B timing and bisection
    #: (and as the escape hatch if Mosaic's sublane-axis dynamic slice
    #: lowers poorly on a TPU generation — the next-TPU-window checklist).
    consensus_gather: bool = True
    #: Per-chunk count re-basing (default on): extend the ``state.rebase``
    #: discipline from clocks to the block-COUNT leaves — at each chunk
    #: boundary the per-owner common base (min blocks of owner o across every
    #: stored prefix count) is subtracted from ``cp``/``own_*``/``height``
    #: and accumulated per run in the carried aux exactly like elapsed time,
    #: then re-added at ``final_stats``. ``count_bound`` then shrinks from a
    #: duration bound to a per-chunk bound (+ a divergence allowance), so
    #: ``state_dtype="auto"`` packs int16 for year-long reference runs
    #: instead of dying at ~106.8 d. Statistics are bit-identical (the
    #: subtraction is linear and every consensus compare is shift-invariant,
    #: pinned by tests/test_consensus_gather.py), so the knob is NOT part of
    #: the sampling identity or checkpoint fingerprint. False restores the
    #: legacy un-rebased counts for A/B and bisection.
    count_rebase: bool = True

    def __post_init__(self) -> None:
        if self.duration_ms <= 0:
            raise ValueError("duration_ms must be positive")
        if self.runs <= 0:
            raise ValueError("runs must be positive")
        if self.mode not in ("auto", "exact", "fast"):
            raise ValueError(f"mode must be auto|exact|fast, got {self.mode!r}")
        if self.rng not in ("threefry", "xoroshiro"):
            raise ValueError(f"rng must be threefry|xoroshiro, got {self.rng!r}")
        if self.group_slots is not None and self.group_slots < 2:
            raise ValueError("group_slots must be >= 2 (or None for auto)")
        if self.chunk_steps is not None and self.chunk_steps < 1:
            raise ValueError("chunk_steps must be >= 1 (or None for auto)")
        if self.superstep is not None and self.superstep < 1:
            raise ValueError("superstep must be >= 1 (or None for auto)")
        if self.flight_capacity < 0:
            raise ValueError("flight_capacity must be >= 0 (0 disables recording)")
        if self.state_dtype not in ("auto", "int32", "int16"):
            raise ValueError(
                f"state_dtype must be auto|int32|int16, got {self.state_dtype!r}"
            )
        if self.state_dtype == "int16" and not self._count_bound_fits_int16:
            plain = self.max_int16_duration_ms(count_rebase=False)
            rebased = self.max_int16_duration_ms(count_rebase=True)
            rebased_s = (
                "any practical duration"
                if rebased >= 1 << 50
                else f"{rebased} (~{rebased / 86_400_000.0:.1f} d)"
            )
            raise ValueError(
                f"state_dtype='int16' requested but the per-run count bound "
                f"({self.count_bound}) exceeds int16 at duration_ms="
                f"{self.duration_ms} (count_rebase={self.count_rebase}); the "
                f"largest duration_ms that fits this roster/interval is "
                f"{plain} (~{plain / 86_400_000.0:.1f} d) without re-basing "
                f"and {rebased_s} with count_rebase=True; use 'auto' (widens "
                f"to int32), enable count_rebase, or shorten the duration"
            )
        # 32-bit time-arithmetic envelope (see tpusim.state docstring): one
        # interval draw must stay far below INTERVAL_CAP = 2^27 ms, and
        # propagation delays below one chunk re-base span.
        if self.network.block_interval_s > 3600.0:
            raise ValueError("block_interval_s above 3600 s exceeds the int32 time envelope")
        if any(m.propagation_ms >= 2**24 for m in self.network.miners):
            raise ValueError("propagation_ms must be below 2^24 ms (~4.7 h)")

    @property
    def max_race_ratio(self) -> float:
        """max propagation delay / mean block interval — the per-block race
        probability scale that bounds fast mode's stale-count shortfall."""
        max_prop_ms = max(m.propagation_ms for m in self.network.miners)
        return max_prop_ms / (self.network.block_interval_s * 1000.0)

    @property
    def resolved_group_slots(self) -> int:
        # Auto resolves to 2 in BOTH modes (round 5; exact was 4 through
        # round 4). Measured basis: selfish reveals push their whole burst
        # as ONE merged (arrival, count) group, so deep buffers are unneeded
        # — at 512 runs x 365 d, selfish40 has 0 overflow-merges in 18.1M
        # blocks (statistics identical to K=4) and honest-10s has 192 in
        # 26.6M (stale-rate shift ~1.2e-6, two orders under the ±1e-4
        # criterion) — while K=2 engages the kernels' dense split-slot path
        # and is faster on every measured engine/config (BASELINE.md round-5
        # notes). Overflow merges stay counted in ``overflow_sum``.
        if self.group_slots is not None:
            return self.group_slots
        return 2

    def _event_bound(self, duration_ms: int) -> int:
        """Per-run event-loop bound over ``duration_ms``: found + arrival
        events at mean + 8 sigma of the Poisson block count. Same formula as
        ``engine.default_n_steps`` (kept inline so this module stays
        jax-free; pinned equal by tests/test_rng_batch.py)."""
        import math

        mu = duration_ms / (self.network.block_interval_s * 1000.0)
        return int(2.0 * (mu + 8.0 * math.sqrt(mu + 1.0))) + 16

    @property
    def resolved_chunk_steps(self) -> int:
        """The chunk-step budget the engine runs at — part of the sampling
        identity (and of checkpoint fingerprints), so it has ONE source,
        jax-free: ``Engine.__init__`` assigns from here and the packed shape
        key (``tpusim.packed.pack_shape_key``) groups points with it without
        building an engine. Default sizing: one TIME_CAP window's MEAN event
        count (~2.05 events per block: find + arrival flush + same-ms
        slack), NOT a tail bound — a run that exhausts its steps before the
        cap resumes next chunk (undershoot costs one more loop iteration),
        while every step past a run's cap is burned on a frozen run, so an
        8-sigma bound wasted ~40% of all scan steps. The 4096 clamp keeps
        short-interval configs from materializing huge (steps, 2, runs)
        per-chunk RNG buffers. Both paths clamp against the *64-aligned*
        event bound: an explicit chunk_steps pinned by
        ``PallasEngine.scan_twin()`` — an already-aligned auto value
        possibly above the raw bound — must resolve to itself, not re-clamp
        to a different identity."""
        bound = self._event_bound(self.duration_ms)
        mu_w = min(TIME_CAP_MS, self.duration_ms) / (
            self.network.block_interval_s * 1000.0
        )
        cap_mean = int(2.05 * mu_w) + 16
        align = lambda v: (v + 63) // 64 * 64
        if self.chunk_steps is None:
            return min(align(min(cap_mean, 4096)), align(bound))
        return min(self.chunk_steps, align(bound))

    def _divergence_allowance(self) -> int:
        """Bound on the count residual a per-chunk re-base can leave behind:
        blocks of one owner above the run's deepest common prefix. Two
        geometric excursions feed it — a selfish miner's private lead (the
        p-vs-(1-p) reveal random walk: P(lead >= L) = (p/(1-p))^L per
        excursion) and propagation-race fork depth (extension probability
        ~2 x race ratio per block) — each bounded as the supremum over the
        run's whole event budget with a union-bounded e^-30 tail, the same
        8-sigma-class exceedance discipline as ``_event_bound``. A
        supercritical roster (selfish majority, or races that never settle)
        gets the full event budget back, i.e. re-basing then buys nothing
        and "auto" stays int32."""
        import math

        n = self._event_bound(self.duration_ms)

        def geom_sup(q: float) -> int:
            if q <= 0.0:
                return 0
            if q >= 1.0:
                return n
            return min(n, int((math.log(2.0 * n) + 30.0) / -math.log(q)) + 1)

        p_selfish = sum(
            m.hashrate_pct for m in self.network.miners if m.selfish
        ) / 100.0
        q_lead = p_selfish / (1.0 - p_selfish) if p_selfish < 0.5 else 1.0
        q_race = min(1.0, 2.0 * self.max_race_ratio)
        return geom_sup(q_lead) + geom_sup(q_race)

    @property
    def count_bound(self) -> int:
        """Upper bound on ANY block-count state value one run can reach —
        the quantity the int16 packing decision is made on.

        Without ``count_rebase`` this is the full-duration event bound
        (``_event_bound``): every height / group count / consensus-tensor
        entry is at most the run's total block count, which is at most half
        the event bound, and the ``stale`` counter's pathological
        multi-count geometries stay well inside the remaining 2x headroom
        (a popped block can only be re-popped after a re-adoption, a
        ~race_ratio^2 event per block).

        With ``count_rebase`` the engines subtract the per-owner common
        base at every chunk boundary, so a stored count is at most the
        post-re-base residual (``_divergence_allowance``) plus one chunk's
        growth — the event bound at the TIME_CAP horizon — and the bound
        stops growing with duration (``stale`` is excluded from packing
        there and stays int32; it is the one monotone accumulator)."""
        if self.count_rebase:
            return (
                self._event_bound(min(self.duration_ms, TIME_CAP_MS))
                + self._divergence_allowance()
            )
        return self._event_bound(self.duration_ms)

    def max_int16_duration_ms(self, *, count_rebase: bool | None = None) -> int:
        """The largest ``duration_ms`` whose ``count_bound`` still fits int16
        for this roster/interval, under the given re-basing mode (default:
        this config's). The int16 ValueError reports both modes so the fix
        — enable ``count_rebase`` vs. shorten the run — is in the message."""
        if count_rebase is None:
            count_rebase = self.count_rebase
        probe = dataclasses.replace(
            self, duration_ms=1, state_dtype="auto", count_rebase=count_rebase
        )
        lo, hi = 0, 1 << 50  # ~35M years: de-facto "unbounded" under re-basing
        while lo < hi:
            mid = (lo + hi + 1) // 2
            fits = dataclasses.replace(probe, duration_ms=mid)._count_bound_fits_int16
            lo, hi = (mid, hi) if fits else (lo, mid - 1)
        return lo

    @property
    def _count_bound_fits_int16(self) -> bool:
        return self.count_bound <= 2**15 - 1

    @property
    def resolved_count_dtype(self) -> str:
        """The dtype actually compiled for the block-count state leaves:
        ``state_dtype`` unless "auto", which packs to int16 exactly when
        :attr:`count_bound` fits — up to ~106.8 days at the 600 s reference
        interval without re-basing (:data:`INT16_MAX_DURATION_MS_600S`);
        with the default ``count_rebase`` the bound is per-chunk and
        year-long reference runs pack too — and widens to int32 otherwise.
        ``stale`` is the exception under re-basing: it is the one monotone
        accumulator, excluded from packing there (it stays int32)."""
        if self.state_dtype != "auto":
            return self.state_dtype
        return "int16" if self._count_bound_fits_int16 else "int32"

    @property
    def resolved_mode(self) -> str:
        if self.mode != "auto":
            return self.mode
        if self.network.any_selfish or self.max_race_ratio > FAST_MODE_MAX_RACE_RATIO:
            return "exact"
        return "fast"

    def to_json(self) -> str:
        return json.dumps(_config_to_dict(self), indent=2)

    @staticmethod
    def from_json(text: str) -> "SimConfig":
        return _config_from_dict(json.loads(text))


def _config_to_dict(cfg: SimConfig) -> dict[str, Any]:
    return {
        "network": {
            "block_interval_s": cfg.network.block_interval_s,
            "miners": [
                {
                    "hashrate_pct": m.hashrate_pct,
                    "propagation_ms": m.propagation_ms,
                    "selfish": m.selfish,
                }
                for m in cfg.network.miners
            ],
        },
        "duration_ms": cfg.duration_ms,
        "runs": cfg.runs,
        "seed": cfg.seed,
        "batch_size": cfg.batch_size,
        "group_slots": cfg.group_slots,
        "mode": cfg.mode,
        "chunk_steps": cfg.chunk_steps,
        "superstep": cfg.superstep,
        "rng": cfg.rng,
        "flight_capacity": cfg.flight_capacity,
        "rng_batch": cfg.rng_batch,
        "state_dtype": cfg.state_dtype,
        "consensus_gather": cfg.consensus_gather,
        "count_rebase": cfg.count_rebase,
    }


def _config_from_dict(d: dict[str, Any]) -> SimConfig:
    net = d["network"]
    miners = tuple(
        MinerConfig(
            hashrate_pct=int(m["hashrate_pct"]),
            propagation_ms=int(m.get("propagation_ms", 1000)),
            selfish=bool(m.get("selfish", False)),
        )
        for m in net["miners"]
    )
    network = NetworkConfig(miners=miners, block_interval_s=float(net.get("block_interval_s", BLOCK_INTERVAL_S)))
    kwargs: dict[str, Any] = {}
    for key in ("duration_ms", "runs", "seed", "batch_size"):
        if key in d:
            kwargs[key] = int(d[key])
    if d.get("group_slots") is not None:
        kwargs["group_slots"] = int(d["group_slots"])
    if d.get("chunk_steps") is not None:
        kwargs["chunk_steps"] = int(d["chunk_steps"])
    if d.get("superstep") is not None:
        kwargs["superstep"] = int(d["superstep"])
    if "mode" in d:
        kwargs["mode"] = str(d["mode"])
    if "flight_capacity" in d:
        kwargs["flight_capacity"] = int(d["flight_capacity"])
    if "rng" in d:
        kwargs["rng"] = str(d["rng"])
    if "rng_batch" in d:
        kwargs["rng_batch"] = bool(d["rng_batch"])
    if "state_dtype" in d:
        kwargs["state_dtype"] = str(d["state_dtype"])
    if "consensus_gather" in d:
        kwargs["consensus_gather"] = bool(d["consensus_gather"])
    if "count_rebase" in d:
        kwargs["count_rebase"] = bool(d["count_rebase"])
    return SimConfig(network=network, **kwargs)
