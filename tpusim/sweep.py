"""Declarative sweep driver for the baseline configuration grids.

The reference's workflow for exploring a parameter space is edit-and-recompile
(reference README.md:21-27, main.cpp:7-10,44-65). Here every BASELINE.json
sweep is a generated list of named SimConfig points that runs from the CLI
with no code edits, emits one JSON line per point (the structured counterpart
of the reference's stdout table), and checkpoints per point so a preempted
TPU job resumes at point granularity.

    python -m tpusim.sweep --list
    python -m tpusim.sweep propagation --runs-scale 0.001 --out prop.jsonl
    python -m tpusim.sweep selfish-threshold --backend cpp --runs-scale 1e-4
"""

from __future__ import annotations

import argparse
import json
import sys
import time
from pathlib import Path
from typing import Callable, Iterable

from .config import MinerConfig, NetworkConfig, SimConfig, default_network
from .provenance import (
    emit_lineage,
    lineage_armed,
    lineage_last,
    lineage_note_parents,
    lineage_take_parents,
)

#: 2025 pool hashrate distribution used across the baseline sweeps.
_DIST_2025 = (30, 29, 12, 11, 8, 5, 3, 1, 1)


def _split_pct(total: int, parts: int) -> tuple[int, ...]:
    """Split an integer percentage into ``parts`` integers summing to total."""
    base, rem = divmod(total, parts)
    return tuple(base + (1 if i < rem else 0) for i in range(parts))


def _selfish_network(selfish_pct: int, propagation_ms: int = 1000) -> NetworkConfig:
    return default_network(
        propagation_ms=propagation_ms,
        selfish_ids=(0,),
        hashrates=(selfish_pct, *_split_pct(100 - selfish_pct, 8)),
    )


def _hetero32_network() -> NetworkConfig:
    """32 miners, heterogeneous propagation: hashrates follow a truncated
    power-law-ish integer split of 100%; propagation spans 100 ms - 60 s."""
    hashrates = [14, 11, 9, 8, 6, 5, 4, 3] + [2] * 16 + [1] * 8
    assert len(hashrates) == 32 and sum(hashrates) == 100
    props = [100 * (600 ** (i / 31)) for i in range(32)]  # 100 ms .. 60 s, log-spaced
    miners = tuple(
        MinerConfig(hashrate_pct=h, propagation_ms=int(p))
        for h, p in zip(hashrates, props)
    )
    return NetworkConfig(miners=miners)


def baseline_sweeps() -> dict[str, Callable[[], list[tuple[str, SimConfig]]]]:
    """The five BASELINE.json sweep grids, as named lazy generators."""

    def reference_default() -> list[tuple[str, SimConfig]]:
        # BASELINE.json configs[0]: 10 s propagation, honest, 365 d, 1024 runs.
        return [
            (
                "ref-10s",
                SimConfig(
                    network=default_network(propagation_ms=10_000),
                    runs=1024,
                ),
            )
        ]

    def propagation() -> list[tuple[str, SimConfig]]:
        # configs[1]: propagation sweep {100ms, 1s, 10s, 60s}, 2^20 runs.
        return [
            (
                f"prop-{ms}ms",
                SimConfig(network=default_network(propagation_ms=ms), runs=2**20),
            )
            for ms in (100, 1000, 10_000, 60_000)
        ]

    def selfish_hashrate() -> list[tuple[str, SimConfig]]:
        # configs[2]: miner-0 selfish, hashrate sweep 25-49%, 8 honest peers.
        return [
            (f"selfish-{pct}pct", SimConfig(network=_selfish_network(pct), runs=2**20))
            for pct in range(25, 50, 3)
        ]

    def hetero32() -> list[tuple[str, SimConfig]]:
        # configs[3]: heterogeneous propagation, 32 miners, 2^22 runs.
        return [("hetero32", SimConfig(network=_hetero32_network(), runs=2**22))]

    def selfish_threshold() -> list[tuple[str, SimConfig]]:
        # configs[4]: block-interval sweep x selfish-threshold grid, 2^24 runs.
        points = []
        for interval_s in (150.0, 300.0, 600.0):
            for pct in (25, 30, 35, 40, 45):
                net = _selfish_network(pct)
                net = NetworkConfig(miners=net.miners, block_interval_s=interval_s)
                points.append(
                    (
                        f"interval-{int(interval_s)}s-selfish-{pct}pct",
                        SimConfig(network=net, runs=2**24),
                    )
                )
        return points

    return {
        "reference-default": reference_default,
        "propagation": propagation,
        "selfish-hashrate": selfish_hashrate,
        "hetero32": hetero32,
        "selfish-threshold": selfish_threshold,
    }


def run_sweep(
    points: Iterable[tuple[str, SimConfig]],
    *,
    backend: str = "tpu",
    runs_scale: float = 1.0,
    out_path: Path | None = None,
    checkpoint_dir: Path | None = None,
    quiet: bool = False,
    resume: bool = False,
    telemetry_path: Path | None = None,
    engine_cache: dict | None = None,
    chaos=None,
    packed: bool = False,
    progress=None,
    use_all_devices: bool = True,
) -> list[dict]:
    """Run every point; returns (and optionally appends as JSONL) result dicts.

    ``runs_scale`` scales each point's run count (floor, min 1) so the full
    2^20-2^24 production grids can be smoke-run at any budget. With
    ``resume``, points whose (name, runs, backend) row already exists in
    ``out_path`` are skipped — so re-running the same command after an
    interrupted hardware window fills exactly the missing points (in-progress
    per-point state is picked up from ``checkpoint_dir`` as usual) without
    appending duplicate rows for finished ones.

    ``telemetry_path`` appends one structured span ledger for the whole
    sweep (tpusim.telemetry): a ``sweep_point`` span per point sharing one
    run_id, with the tpu backend's per-batch spans interleaved under the
    same id — render with ``python -m tpusim report``. Inside a fleet
    packed-grid worker the recorder adopts the supervisor's trace context
    from ``TPUSIM_TRACE_CONTEXT`` (tpusim.tracing), so the sub-grid's spans
    land in the fleet's span tree under the fleet run_id — which is why the
    report dashboards partition their panels by ``(run_id, process)``.

    ``engine_cache`` shares compiled engines across same-shape grid points
    (tpusim.runner.make_engine): a sweep like selfish-hashrate varies only
    the roster percentages — runtime inputs of the jitted programs — so
    every point after the first rebinds the warm engine instead of
    recompiling (pinned by tests/test_sweep_engine_cache.py). Defaults to a
    fresh per-call cache on the tpu backend; pass a dict to share across
    calls.

    ``chaos`` (tpusim.chaos: plan, injector, or plan-JSON path) arms fault
    injection: a ``sweep.point`` seam fires before each point (so a drill
    can poison one named point), and the injector is threaded into the tpu
    backend's own seams. A poisoned point fails LOUD and kills the sweep —
    the recovery story is re-running with ``resume=True`` and WITHOUT the
    chaos plan (a fresh process re-arms every fault count, so resuming with
    the same plan just dies at the same point), which fills exactly the
    missing points (tests/test_chaos.py pins the refilled rows bit-equal to
    a fault-free sweep).

    ``packed`` (tpu backend only — tpusim.packed) runs the grid as packed
    device programs instead of per-point dispatches: points that agree in
    program shape (tpusim.packed.pack_shape_key) share ONE compiled program
    with their scenario parameters as per-run runtime tensors, and their
    rows are BIT-equal to the sequential sweep (minus the wall-clock
    fields). ``rng="xoroshiro"`` grids pack with per-run stream seeds,
    flight-recorder grids pack with per-piece ring decode, and
    ``checkpoint_dir`` writes the SAME per-point npz checkpoints as the
    sequential path after every packed dispatch — so a killed packed sweep
    resumes mid-pack, interchangeably with a sequential resume (README
    "Grid packing": device meshes / multi-controller are the only remaining
    carve-outs). Rows keep the exact schema and point order either way.

    ``progress(done_runs, total_runs)`` fires as runs complete, cumulative
    over the WHOLE sweep (tpu backend; packed dispatches report per
    dispatch) — the runner's callback contract, so a fleet worker's
    heartbeat covers sub-grid units too. ``use_all_devices=False`` keeps
    every point on one device (the fleet's ``--single-device`` lever for
    workers sharing a host); packed dispatches are single-device either way.
    """
    import dataclasses

    from .backend import get_backend
    from .chaos import as_injector

    chaos = as_injector(chaos)
    if engine_cache is None:
        engine_cache = {}

    if backend not in ("tpu", "cpp"):
        raise ValueError(
            f"run_sweep supports the 'tpu' and 'cpp' backends, got {backend!r} "
            f"(the pychain oracle returns raw chains, not SimResults)"
        )
    if packed and backend != "tpu":
        raise ValueError("packed sweeps need the tpu backend")

    done: set[tuple[str, int, str]] = set()
    if resume and out_path is not None and out_path.exists():
        for line in out_path.read_text().splitlines():
            if not line.strip():
                continue
            # A killed window (timeout -k mid-write) can leave a truncated
            # trailing line, and pre-round-5 rows carry no "point" key; a
            # resume pass must treat both as not-done, not crash on them.
            try:
                row = json.loads(line)
                done.add((row["point"], row["runs"], row["backend"]))
            except (json.JSONDecodeError, KeyError):
                continue

    recorder = None
    if telemetry_path is not None:
        from .telemetry import TelemetryRecorder

        recorder = TelemetryRecorder(telemetry_path)
        if chaos is not None:
            chaos.bind_telemetry(recorder)
            recorder.chaos = chaos

    def emit_row(row: dict, runs: int) -> None:
        if lineage_armed():
            # The row's lineage record, content-addressed over the EXACT dict
            # written below (json round-trips floats exactly, so the on-disk
            # row re-hashes to the same address). Parents come from the
            # point-keyed mailbox: run_one files the run record that produced
            # the row; a packed resume files its checkpoint_load. Emitted
            # even with no out_path — fleet grid workers run this path and
            # the supervisor writes their rows verbatim.
            emit_lineage(
                "sweep_row", content=row,
                parents=lineage_take_parents(row["point"]),
                point=row["point"], runs=runs, backend=backend,
            )
        if out_path is not None:
            # Torn-trailing-line repair before every append (a killed window
            # can cut the previous row mid-write) — the shared discipline of
            # telemetry.append_jsonl_line, also used by the fleet ledger.
            from .telemetry import append_jsonl_line

            append_jsonl_line(out_path, json.dumps(row))
        if recorder is not None:
            recorder.emit(
                "sweep_point", t_start=time.time() - row["elapsed_s"],
                dur_s=row["elapsed_s"], point=row["point"], runs=runs,
                backend=backend,
            )
        if not quiet:
            print(f"[{row['point']}] done in {row['elapsed_s']}s ({runs} runs)")

    def run_one(name: str, config: SimConfig) -> dict:
        if chaos is not None:
            # The poisoned-point seam: fires before any compute so a drill
            # can poison one named point and fail loud — an operator resumes
            # with --resume, which fills exactly the missing points.
            chaos.fire("sweep.point", target=name, backend=backend)
        t0 = time.monotonic()
        if backend == "tpu":
            kwargs = {"engine_cache": engine_cache, "chaos": chaos,
                      "use_all_devices": use_all_devices}
            if progress is not None:
                base = runs_done_acc["n"]
                kwargs["progress"] = (
                    lambda d, t: progress(base + d, total_runs)
                )
            if checkpoint_dir is not None:
                checkpoint_dir.mkdir(parents=True, exist_ok=True)
                kwargs["checkpoint_path"] = checkpoint_dir / f"{name}.npz"
            if recorder is not None:
                # The backend's batch/checkpoint spans share the sweep's
                # run_id, so one ledger correlates the whole grid.
                kwargs["telemetry"] = recorder
            res = get_backend("tpu")(config, **kwargs)
            if lineage_armed():
                # File the run record the backend just emitted as this
                # point's parent; emit_row pops the mailbox when the row
                # lands (possibly after later points finish, under the
                # buffered point-order flush).
                lineage_note_parents(name, lineage_last("run"))
        else:
            res = get_backend(backend)(config)
        # Spread first: the sweep's own wall-clock (which includes checkpoint
        # setup and native build overhead) must win over the backend-internal
        # elapsed_s inside to_dict().
        return {
            **res.to_dict(),
            "point": name,
            "backend": backend,
            "elapsed_s": round(time.monotonic() - t0, 3),
        }

    pending: list[tuple[str, SimConfig]] = []
    for name, config in points:
        runs = max(1, int(config.runs * runs_scale))
        if (name, runs, backend) in done:
            if not quiet:
                print(f"[{name}] already in {out_path}; skipping")
            continue
        pending.append((name, dataclasses.replace(config, runs=runs)))

    sweep_t0 = time.monotonic()
    rows_by_idx: dict[int, dict] = {}
    flushed = 0
    # Sweep-cumulative progress base: packs and points run serially, so a
    # running offset turns their per-group callbacks into one monotone
    # (done, total) stream for the caller's heartbeat.
    total_runs = sum(cfg.runs for _, cfg in pending)
    runs_done_acc = {"n": 0}

    def flush() -> None:
        # Rows land in POINT order (the fleet's buffered-flush rule): a row
        # is appended only once every earlier point's row exists, so packed
        # output files diff line-for-line against sequential ones. The
        # sequential path completes points in order, so it still streams.
        nonlocal flushed
        while flushed < len(pending) and flushed in rows_by_idx:
            emit_row(rows_by_idx[flushed], pending[flushed][1].runs)
            flushed += 1

    if packed and pending:
        from .packed import plan_packs, run_grid

        packs, sequential = plan_packs(pending)
        for pack in packs:
            # The per-point chaos seam still fires per point, before the
            # pack's first compute — same drill surface as the sequential
            # path (a poisoned point kills the whole pack, loud).
            if chaos is not None:
                for i in pack.indices:
                    chaos.fire(
                        "sweep.point", target=pending[i][0], backend=backend
                    )
            group = [pending[i] for i in pack.indices]
            base = runs_done_acc["n"]
            out = run_grid(
                group, engine_cache=engine_cache, telemetry=recorder,
                chaos=chaos, checkpoint_dir=checkpoint_dir,
                progress=None if progress is None else (
                    lambda d, t: progress(base + d, total_runs)
                ),
            )
            runs_done_acc["n"] = base + sum(cfg.runs for _, cfg in group)
            for i, entry in zip(pack.indices, out):
                rows_by_idx[i] = {
                    **entry["results"].to_dict(),
                    "point": entry["name"],
                    "backend": backend,
                    "elapsed_s": round(entry["elapsed_s"], 3),
                }
            flush()
        for i in sequential:
            rows_by_idx[i] = run_one(*pending[i])
            runs_done_acc["n"] += pending[i][1].runs
            flush()
    else:
        for i, (name, config) in enumerate(pending):
            rows_by_idx[i] = run_one(name, config)
            runs_done_acc["n"] += config.runs
            flush()

    results = [rows_by_idx[i] for i in range(len(pending))]
    if recorder is not None:
        if packed:
            # Packed grids never enter the runner, so nothing else emits the
            # closing "run" span `tpusim watch` exits on — the sweep owns it
            # (the fleet supervisor's discipline).
            elapsed = time.monotonic() - sweep_t0
            recorder.emit(
                "run", t_start=time.time() - elapsed, dur_s=elapsed,
                points=len(results), packed=True, backend=backend,
            )
        recorder.close()
    return results


def main(argv: list[str] | None = None) -> int:
    sweeps = baseline_sweeps()
    p = argparse.ArgumentParser(prog="tpusim.sweep", description=__doc__)
    p.add_argument("sweep", nargs="?", choices=sorted(sweeps), help="which baseline grid")
    p.add_argument("--list", action="store_true", help="list sweeps and their points")
    p.add_argument("--backend", default="tpu", choices=("tpu", "cpp"))
    p.add_argument("--runs-scale", type=float, default=1.0)
    p.add_argument(
        "--max-points", type=int, default=None,
        help="run only the first N points of the grid (full-scale runs in "
        "bounded hardware windows; the rest resume via --checkpoint-dir)",
    )
    p.add_argument(
        "--resume", action="store_true",
        help="skip points whose (point, runs, backend) row already exists in "
        "--out — re-running the identical command after an interrupted "
        "window fills exactly the missing points without appending duplicate "
        "rows (whose elapsed_s would reflect only the checkpoint reload)",
    )
    p.add_argument("--out", type=Path, help="append one JSON line per point here")
    p.add_argument("--checkpoint-dir", type=Path, help="per-point npz checkpoints (tpu backend)")
    p.add_argument(
        "--telemetry", type=Path, metavar="JSONL",
        help="append one structured span ledger for the sweep here "
        "(render with `python -m tpusim report`)",
    )
    p.add_argument(
        "--packed", action="store_true",
        help="run shape-agreeing grid points as packed device programs "
        "(tpusim.packed): one compiled program per shape group, scenario "
        "params as per-run tensors, rows bit-equal to the sequential sweep; "
        "xoroshiro and flight-recorder grids pack too, and --checkpoint-dir "
        "writes the sequential path's per-point npz after every dispatch "
        "(mid-pack resume)",
    )
    p.add_argument("--quiet", action="store_true")
    p.add_argument(
        "--no-probe", action="store_true",
        help="skip the pre-flight accelerator probe (tpu backend only)",
    )
    p.add_argument(
        "--chaos", type=Path, metavar="PLAN",
        help="JSON chaos plan (tpusim.chaos): deterministic fault-injection "
        "drill across the probe, dispatch, checkpoint and telemetry seams",
    )
    args = p.parse_args(argv)

    chaos = None
    if args.chaos is not None:
        from .chaos import ChaosInjector, load_plan

        chaos = ChaosInjector(load_plan(args.chaos))

    if args.list or not args.sweep:
        for name, gen in sorted(sweeps.items()):
            points = gen()
            total = sum(c.runs for _, c in points)
            print(f"{name}: {len(points)} points, {total} total runs")
            for pname, c in points:
                print(f"  - {pname}: {c.network.n_miners} miners, {c.runs} runs")
        return 0

    if args.backend == "tpu" and not args.no_probe:
        # The tunneled TPU backend can wedge jax.devices() inside this
        # process where nothing can time it out; prove the backend from a
        # killable subprocess first and fail loudly instead of hanging a
        # multi-hour sweep at init (tpusim.probe).
        from .probe import probe_backend

        platform = probe_backend(chaos=chaos)
        if platform is None:
            print(
                "error: accelerator backend unavailable after probe retries; "
                "re-run later, with --backend cpp, or with --no-probe",
                file=sys.stderr,
            )
            return 2
        if platform != "tpu":
            # The JAX engine runs anywhere; a CPU-only environment is a
            # legitimate (if slow) place to smoke a sweep — say so loudly.
            print(
                f"warning: no TPU visible (platform={platform}); the sweep "
                f"will run on {platform}",
                file=sys.stderr,
            )

    points = sweeps[args.sweep]()
    if args.max_points is not None:
        points = points[: args.max_points]
    run_sweep(
        points,
        backend=args.backend,
        runs_scale=args.runs_scale,
        out_path=args.out,
        checkpoint_dir=args.checkpoint_dir,
        quiet=args.quiet,
        resume=args.resume,
        telemetry_path=args.telemetry,
        chaos=chaos,
        packed=args.packed,
    )
    return 0


if __name__ == "__main__":
    sys.exit(main())
