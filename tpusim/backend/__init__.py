"""Simulation backends behind the ``SimBackend`` boundary.

  * ``tpu``     — the JAX engine (tpusim.engine); default, used by the runner.
  * ``pychain`` — a literal materialized-chain simulator in pure Python with
    the reference's exact semantics; the in-repo behavioral oracle.
  * ``cpp``     — a native C++ re-implementation (compiled on demand), the
    performance-credible cross-validation oracle, replacing the reference's
    std::async runner (main.cpp:195-220).
"""

from __future__ import annotations

from typing import Callable


def get_backend(name: str) -> Callable:
    if name == "tpu":
        from ..api import run_simulation

        return run_simulation
    if name == "pychain":
        from .pychain import run_simulation_pychain

        return run_simulation_pychain
    if name == "cpp":
        from .cpp import run_simulation_cpp

        return run_simulation_cpp
    raise KeyError(f"unknown backend {name!r}; have tpu, pychain, cpp")


__all__ = ["get_backend"]
