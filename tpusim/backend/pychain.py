"""Literal materialized-chain simulator: the in-repo behavioral oracle.

This backend keeps every miner's chain as an explicit list, exactly like the
reference's ``std::vector<Block>`` model (reference simulation.h:41-202,
main.cpp:68-192), so the O(1)-state TPU automaton can be checked against it
block by block (tests/test_state_equivalence.py). It is intentionally simple
and slow; it exists for correctness, not throughput.

Blocks are (owner, arrival) pairs with ``arrival is None`` for a selfish
miner's private blocks (the reference's SELFISH_ARRIVAL sentinel,
simulation.h:20). The genesis block is implicit: chain lists exclude it, and
an empty published chain has tip arrival 0 (Block::Genesis, simulation.h:31-33).
"""

from __future__ import annotations

import dataclasses
from typing import Sequence

from ..config import SimConfig

Block = tuple[int, int | None]  # (owner_idx, arrival_ms or None for private)


@dataclasses.dataclass
class ChainMiner:
    idx: int
    propagation_ms: int
    selfish: bool
    chain: list[Block] = dataclasses.field(default_factory=list)
    stale: int = 0

    # -- chain queries (reference simulation.h:79-121) ----------------------
    def unpublished(self, t: int) -> int:
        n = 0
        for owner, arrival in reversed(self.chain):
            if arrival is not None and arrival <= t:
                break
            n += 1
        return n

    def published_chain(self, t: int) -> list[Block]:
        n = self.unpublished(t)
        return self.chain[: len(self.chain) - n]

    def next_arrival(self, t: int) -> int | None:
        earliest: int | None = None
        for owner, arrival in reversed(self.chain):
            if arrival is not None and arrival <= t:
                break
            if arrival is not None:
                earliest = arrival
        return earliest

    def private_len(self) -> int:
        n = 0
        for owner, arrival in reversed(self.chain):
            if arrival is not None:
                break
            n += 1
        return n

    # -- events (reference simulation.h:62-76,124-180) ----------------------
    def found_block(self, t: int, best_chain_len: int) -> None:
        """best_chain_len counts blocks excluding genesis."""
        if self.selfish:
            one_block_race = self.private_len() == 1 and best_chain_len == len(self.chain)
            if one_block_race:
                owner, _ = self.chain[-1]
                self.chain[-1] = (owner, t + self.propagation_ms)
                self.chain.append((self.idx, t + self.propagation_ms))
            else:
                self.chain.append((self.idx, None))
        else:
            self.chain.append((self.idx, t + self.propagation_ms))

    def maybe_selfish_reveal(self, best: list[Block], t: int) -> None:
        if not self.selfish or len(best) > len(self.chain):
            return
        private = self.private_len()
        lead = len(self.chain) - len(best)
        if private > lead:
            reveal = private if (private > 1 and lead == 1) else private - lead
            start = len(self.chain) - private
            for i in range(start, start + reveal):
                self.chain[i] = (self.chain[i][0], t + self.propagation_ms)

    def maybe_reorg(self, best: list[Block]) -> None:
        if len(best) <= len(self.chain):
            return
        while self.chain and self.chain[-1] != best[len(self.chain) - 1]:
            owner, _ = self.chain.pop()
            if owner == self.idx:
                self.stale += 1
        self.chain.extend(best[len(self.chain) :])

    def notify(self, best: list[Block], t: int) -> None:
        self.maybe_selfish_reveal(best, t)
        self.maybe_reorg(best)


def best_chain(miners: Sequence[ChainMiner], t: int) -> list[Block]:
    """Longest published chain, first-seen tiebreak (reference main.cpp:68-82).
    Genesis is implicit: an empty published chain has tip arrival 0."""
    best: list[Block] = []
    have = False
    for miner in miners:
        pub = miner.published_chain(t)
        tip = pub[-1][1] if pub else 0
        best_tip = best[-1][1] if best else 0
        if not have or len(pub) > len(best) or (len(pub) == len(best) and tip < best_tip):
            best = pub
            have = True
    return list(best)


def earliest_arrival(miners: Sequence[ChainMiner], t: int) -> int | None:
    earliest: int | None = None
    for miner in miners:
        a = miner.next_arrival(t)
        if a is not None and (earliest is None or a < earliest):
            earliest = a
    return earliest


def run_chain_sim(
    config: SimConfig, intervals: Sequence[int], winners: Sequence[int]
) -> dict[str, list]:
    """One run driven by pre-drawn (interval, winner) sequences.

    Event loop semantics of the reference (main.cpp:128-192): drain all block
    finds due at the current time, recompute the best chain, notify every
    miner, then cut through to the earliest next event. Returns per-miner
    stats measured against the best chain at ``duration`` (main.cpp:185-191)
    plus the raw final chains for state-equivalence checks.
    """
    miners = [
        ChainMiner(idx=i, propagation_ms=mc.propagation_ms, selfish=mc.selfish)
        for i, mc in enumerate(config.network.miners)
    ]
    duration = config.duration_ms
    i_interval, i_winner = 1, 0
    next_block = int(intervals[0])
    best_len_prev = 0  # genesis-only best chain

    t = 0
    while t < duration:
        while t == next_block:
            miners[winners[i_winner]].found_block(t, best_len_prev)
            i_winner += 1
            next_block += int(intervals[i_interval])
            i_interval += 1
        best = best_chain(miners, t)
        for miner in miners:
            miner.notify(best, t)
        best_len_prev = len(best)
        arrival = earliest_arrival(miners, t)
        t = next_block if arrival is None else min(next_block, arrival)

    final_best = best_chain(miners, duration)
    found = [sum(1 for owner, _ in final_best if owner == m.idx) for m in miners]
    denom = max(len(final_best), 1)
    return {
        "blocks_found": found,
        "blocks_share": [f / denom if f > 0 else 0.0 for f in found],
        "stale_rate": [m.stale / f if f > 0 else 0.0 for m, f in zip(miners, found)],
        "stale_blocks": [m.stale for m in miners],
        "best_height": len(final_best),
        "chains": [list(m.chain) for m in miners],
    }


def run_simulation_pychain(config: SimConfig, rng=None) -> dict[str, list]:
    """Multi-run pychain backend with numpy-drawn events (statistical use).

    Intervals follow the reference pipeline in float64: exponential drawn in
    ns, rounded, truncated to ms (reference simulation.h:205-210). The TPU
    engine's float32 floor-of-exponential (tpusim.sampling.interval_from_bits)
    agrees with this to 1 ms on all but ~1e-4 of draws; cross-validation
    between the backends is distributional, not bitwise."""
    import numpy as np

    rng = np.random.default_rng(config.seed if rng is None else rng)
    pcts = np.array([m.hashrate_pct for m in config.network.miners], dtype=np.float64)
    probs = pcts / pcts.sum()
    mean_ns = config.network.block_interval_s * 1e9
    expected_blocks = config.duration_ms / (config.network.block_interval_s * 1000.0)
    n_draw = int(2 * expected_blocks + 100)

    totals = {"blocks_found": 0.0, "blocks_share": 0.0, "stale_rate": 0.0}
    per_run = []
    for _ in range(config.runs):
        intervals = (np.rint(rng.exponential(mean_ns, size=n_draw)).astype(np.int64) // 1_000_000)
        winners = rng.choice(len(probs), size=n_draw, p=probs)
        per_run.append(run_chain_sim(config, intervals.tolist(), winners.tolist()))
    return {
        "per_run": per_run,
        "blocks_found_mean": [
            sum(r["blocks_found"][i] for r in per_run) / config.runs
            for i in range(config.network.n_miners)
        ],
        "blocks_share_mean": [
            sum(r["blocks_share"][i] for r in per_run) / config.runs
            for i in range(config.network.n_miners)
        ],
        "stale_rate_mean": [
            sum(r["stale_rate"][i] for r in per_run) / config.runs
            for i in range(config.network.n_miners)
        ],
    }
