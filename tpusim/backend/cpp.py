"""ctypes bindings for the native C++ backend (native/simcore.cpp).

The native library is the framework's performance-credible cross-validation
oracle: an independent materialized-chain implementation of the simulation
semantics with the reference's std::async-style run-level threading
(reference main.cpp:195-220) re-done as deterministic static partitioning.
It is compiled on demand with the in-tree Makefile (g++ only; no pybind11 —
the ABI is 5 flat arrays, ctypes is the right amount of machinery).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
from pathlib import Path

import numpy as np

from ..config import SimConfig
from ..stats import SimResults

_NATIVE_DIR = Path(__file__).resolve().parent.parent.parent / "native"
_LIB_PATH = _NATIVE_DIR / "libsimcore.so"
_SRC_PATH = _NATIVE_DIR / "simcore.cpp"

_lib: ctypes.CDLL | None = None


class NativeBuildError(RuntimeError):
    pass


def _ensure_built() -> Path:
    # TPUSIM_SIMCORE_LIB points the bindings at an alternative prebuilt
    # library — the ci.sh sanitizer leg loads libsimcore_san.so (ASan/UBSan
    # instrumented, LD_PRELOADed runtime) through the exact same Python
    # harness as the production library, so the xoroshiro A/B and trace-diff
    # contracts run under the sanitizers instead of only the C++ smoke.
    override = os.environ.get("TPUSIM_SIMCORE_LIB")
    if override:
        p = Path(override)
        if not p.exists():
            raise NativeBuildError(f"TPUSIM_SIMCORE_LIB={override} does not exist")
        return p
    if not _SRC_PATH.exists():
        raise NativeBuildError(f"native source missing at {_SRC_PATH}")
    # Always invoke make: it is a no-op when up to date and, unlike a
    # hand-rolled mtime check, also rebuilds on Makefile/flag changes.
    try:
        proc = subprocess.run(
            ["make", "-C", str(_NATIVE_DIR), "libsimcore.so"],
            capture_output=True,
            text=True,
        )
    except FileNotFoundError:
        # No build toolchain on PATH: a prebuilt library is still usable.
        if _LIB_PATH.exists():
            return _LIB_PATH
        raise NativeBuildError("make not found and no prebuilt libsimcore.so") from None
    if proc.returncode != 0:
        raise NativeBuildError(
            f"building libsimcore.so failed:\n{proc.stdout}\n{proc.stderr}"
        )
    return _LIB_PATH


def _load() -> ctypes.CDLL:
    global _lib
    if _lib is None:
        lib = ctypes.CDLL(str(_ensure_built()))
        dp = ctypes.POINTER(ctypes.c_double)
        lib.simcore_run.restype = ctypes.c_int
        lib.simcore_run.argtypes = [
            ctypes.c_int32,  # n_miners
            ctypes.POINTER(ctypes.c_int32),  # hashrate_pct
            ctypes.POINTER(ctypes.c_int64),  # prop_ms
            ctypes.POINTER(ctypes.c_uint8),  # selfish
            ctypes.c_int64,  # duration_ms
            ctypes.c_double,  # block_interval_s
            ctypes.c_int64,  # runs
            ctypes.c_uint64,  # seed
            ctypes.c_int32,  # threads
            dp, dp, dp, dp, dp,  # found, share, stale_rate, stale_blocks, best_height
        ]
        # Guarded: a PREBUILT libsimcore.so from before the trace producer
        # (the make-less fallback in _ensure_built) must keep serving
        # run_simulation_cpp; only run_events_cpp needs the new symbol and
        # it re-checks with a rebuild hint.
        if hasattr(lib, "simcore_run_events"):
            lib.simcore_run_events.restype = ctypes.c_int
            lib.simcore_run_events.argtypes = [
                ctypes.c_int32,  # n_miners
                ctypes.POINTER(ctypes.c_int32),  # hashrate_pct
                ctypes.POINTER(ctypes.c_int64),  # prop_ms
                ctypes.POINTER(ctypes.c_uint8),  # selfish
                ctypes.c_int64,  # duration_ms
                ctypes.c_double,  # block_interval_s
                ctypes.c_int64,  # runs
                ctypes.c_uint64,  # seed
                ctypes.c_char_p,  # events_path
                ctypes.POINTER(ctypes.c_int64),  # n_events_out
            ]
        _lib = lib
    return _lib


def run_events_cpp(config: SimConfig, events_path) -> int:
    """Run ``config`` on the native backend with event tracing and write the
    flight-recorder-schema JSONL event log to ``events_path`` — the native
    half of the README "Event tracing" cross-backend diff recipe (the JAX
    half is ``tpusim trace --rng xoroshiro --events-out``; compare with
    ``tpusim trace diff``). Single-threaded by design (traces are for runs
    small enough to read). Returns the number of events written."""
    lib = _load()
    if not hasattr(lib, "simcore_run_events"):
        raise NativeBuildError(
            "libsimcore.so predates the event-trace producer; rebuild it "
            "(make -C native libsimcore.so)"
        )
    n = config.network.n_miners
    pct = np.array([m.hashrate_pct for m in config.network.miners], dtype=np.int32)
    prop = np.array([m.propagation_ms for m in config.network.miners], dtype=np.int64)
    selfish = np.array([m.selfish for m in config.network.miners], dtype=np.uint8)
    n_events = ctypes.c_int64(0)
    rc = lib.simcore_run_events(
        n,
        pct.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        prop.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        selfish.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        config.duration_ms,
        config.network.block_interval_s,
        config.runs,
        config.seed,
        str(events_path).encode(),
        ctypes.byref(n_events),
    )
    if rc == 3:
        # Open failure OR a torn write (the native side removes the partial
        # file, mirroring flight_export._write_artifact's fail-clean rule).
        raise OSError(
            f"simcore_run_events could not open or fully write {events_path}"
        )
    if rc != 0:
        raise ValueError(f"simcore_run_events rejected the configuration (code {rc})")
    return int(n_events.value)


def run_simulation_cpp(config: SimConfig, threads: int | None = None) -> SimResults:
    """Run ``config`` on the native backend; returns the same SimResults shape
    as the JAX engine path, so results are directly comparable."""
    lib = _load()
    n = config.network.n_miners
    pct = np.array([m.hashrate_pct for m in config.network.miners], dtype=np.int32)
    prop = np.array([m.propagation_ms for m in config.network.miners], dtype=np.int64)
    selfish = np.array([m.selfish for m in config.network.miners], dtype=np.uint8)
    found = np.zeros(n, np.float64)
    share = np.zeros(n, np.float64)
    stale_rate = np.zeros(n, np.float64)
    stale_blocks = np.zeros(n, np.float64)
    best = np.zeros(1, np.float64)

    import time

    t0 = time.monotonic()
    rc = lib.simcore_run(
        n,
        pct.ctypes.data_as(ctypes.POINTER(ctypes.c_int32)),
        prop.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
        selfish.ctypes.data_as(ctypes.POINTER(ctypes.c_uint8)),
        config.duration_ms,
        config.network.block_interval_s,
        config.runs,
        config.seed,
        0 if threads is None else threads,
        found.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        share.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        stale_rate.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        stale_blocks.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
        best.ctypes.data_as(ctypes.POINTER(ctypes.c_double)),
    )
    if rc != 0:
        raise ValueError(f"simcore_run rejected the configuration (code {rc})")
    elapsed = time.monotonic() - t0

    sums = {
        "runs": np.int64(config.runs),
        "blocks_found_sum": found,
        "blocks_share_sum": share,
        "stale_rate_sum": stale_rate,
        "stale_blocks_sum": stale_blocks,
        "best_height_sum": best[0],
        "overflow_sum": np.int64(0),
    }
    return SimResults.from_sums(sums, config, mode="cpp", elapsed_s=elapsed, compile_s=0.0)
