"""Public API: ``run_simulation(config) -> SimResults``."""

from __future__ import annotations

from .config import SimConfig
from .stats import SimResults


def run_simulation(config: SimConfig, **kwargs) -> SimResults:
    """Run a full Monte-Carlo simulation as configured.

    Library-level equivalent of the reference's ``main()`` driver
    (main.cpp:195-235). See :func:`tpusim.runner.run_simulation_config` for
    orchestration keyword arguments (mesh, checkpoint_path, progress, ...).
    """
    from .runner import run_simulation_config

    return run_simulation_config(config, **kwargs)
