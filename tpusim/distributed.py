"""Multi-host execution over DCN — the framework's distributed backend.

The reference's only parallelism is std::async threads in one process
(reference main.cpp:195-220); SURVEY.md §2.2 maps that to the TPU-native
stack: runs sharded over all chips of all hosts via ``shard_map`` with the
statistics reduction as an on-device ``psum`` (ICI within a slice, DCN across
hosts), coordinated by ``jax.distributed`` — the multi-controller JAX recipe,
not an MPI/NCCL port. No point-to-point communication exists anywhere: runs
are independent, so the one collective is the final reduction.

Usage on each host of a multi-host TPU pod slice::

    from tpusim.distributed import initialize, run_simulation_distributed
    initialize(coordinator_address="host0:8476", num_processes=N, process_id=i)
    results = run_simulation_distributed(config)   # identical on every host

Single-process usage degrades to the plain runner (and is what the test
suite exercises; multi-host needs real DCN-connected hosts).
"""

from __future__ import annotations

import logging

import numpy as np
import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from .config import SimConfig
from .runner import make_run_keys, run_simulation_config
from .stats import SimResults

logger = logging.getLogger("tpusim")

__all__ = ["initialize", "global_mesh", "make_global_keys", "run_simulation_distributed"]


def initialize(
    coordinator_address: str | None = None,
    num_processes: int | None = None,
    process_id: int | None = None,
) -> None:
    """Bring up the multi-controller runtime (jax.distributed over DCN).

    On cloud TPU pods all three arguments are discovered from the metadata
    server and may be omitted — a bare ``initialize()`` forwards to
    ``jax.distributed.initialize()``'s auto-discovery. Call once per process,
    before any other JAX call. Pass ``num_processes=1`` explicitly for a
    single-process run; that is a no-op, so one program can serve both modes
    with only its process-count argument changing.
    """
    if num_processes == 1:
        logger.info("single-process run; jax.distributed not initialized")
        return
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
    )
    logger.info(
        "distributed runtime up: process %d/%d, %d global / %d local devices",
        jax.process_index(), jax.process_count(),
        len(jax.devices()), len(jax.local_devices()),
    )


def global_mesh() -> Mesh:
    """One-axis mesh over every device of every process; the runs axis of a
    batch is sharded across it and stat sums ride psum (ICI, then DCN)."""
    return Mesh(np.array(jax.devices()), ("runs",))


def make_global_keys(seed: int, start: int, count: int, mesh: Mesh) -> jax.Array:
    """Per-run keys for a globally-sharded batch.

    Under multi-controller JAX an addressable array must be assembled from
    each process's local shard; every run keeps the same (seed, run-index)
    key it would have in a single-process run, so results are independent of
    the process layout (the distributed analogue of the run-order-invariant
    reduction in the native backend).
    """
    sharding = NamedSharding(mesh, P("runs"))
    if jax.process_count() == 1:
        return jax.device_put(make_run_keys(seed, start, count), sharding)

    def local_shard(index) -> np.ndarray:
        lo = index[0].start or 0
        hi = index[0].stop if index[0].stop is not None else count
        return np.asarray(jax.random.key_data(make_run_keys(seed, start + lo, hi - lo)))

    shape = jax.eval_shape(lambda: jax.random.key_data(make_run_keys(seed, 0, count))).shape
    data = jax.make_array_from_callback(shape, sharding, local_shard)
    return jax.random.wrap_key_data(data)


def run_simulation_distributed(config: SimConfig, **kwargs) -> SimResults:
    """Run ``config`` sharded over every device of every host.

    Every process must call this with the identical config; all return the
    identical results (psum leaves the reduced sums replicated). Batch size
    is rounded to the global device count by the runner. Checkpointing works
    at batch granularity exactly as in the single-host runner — on
    preemption, restart all processes and resume.
    """
    mesh = global_mesh()
    if jax.process_count() > 1 and config.runs % mesh.devices.size != 0:
        raise ValueError(
            f"multi-host runs ({config.runs}) must be a multiple of the global "
            f"device count ({mesh.devices.size}) so every process sees full batches"
        )
    return run_simulation_config(config, mesh=mesh, **kwargs)
